"""Distributed KVStore: multi-host parameter service over TCP (DCN path).

Capability parity with the reference's ps-lite stack:
``KVStoreDist`` (``src/kvstore/kvstore_dist.h:44``, worker side),
``KVStoreDistServer`` (``src/kvstore/kvstore_dist_server.h:155``, server
side: ``DataHandleEx:325``, sync aggregation ``ApplyUpdates:346`` that
waits for all workers per key, async immediate-apply mode, server-side
optimizer execution), key sharding across servers (``EncodeDefaultKey:263``),
row-sparse pulls (``:344-373``), and 2-bit gradient compression with
error-feedback residual (``gradient_compression.h:43-130``).

TPU-native stance: *intra-host* reduction rides ICI inside compiled
executables (``parallel.JitTrainStep`` psum) — this module is the
*inter-host* (DCN) tier, where the reference used ZMQ.  The wire is a
TYPED binary protocol over TCP (the shape of ps-lite's message format,
``kvstore_dist.h:267-327``): every frame is a magic+version+command
header followed by tagged fields (string / raw-tensor / float64 / json
/ bytes) — never pickled objects, so a hostile peer can inject data at
worst, not code.  Connections open with a shared-secret HMAC handshake
(``MXNET_KVSTORE_SECRET`` env, set by ``tools/launch.py``); the
scheduler rendezvous of ps-lite collapses into the servers themselves
(workers connect straight to the server addresses derived from the root
URI) — one fewer process with identical observable semantics.

Server-side optimizers travel as a JSON config (registry name +
scalar hyperparameters), not a code object; optimizers carrying an
``lr_scheduler`` must schedule worker-side (documented limitation —
the reference shipped the whole pickled object, an RCE by design).

Environment (reference names, ``tools/launch.py`` sets them):
``DMLC_ROLE`` (worker|server|scheduler), ``DMLC_PS_ROOT_URI``,
``DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``, ``DMLC_NUM_SERVER``,
plus ``MXNET_KVSTORE_SECRET`` (optional shared secret).
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import os
import secrets as _secrets
import socket
import struct
import threading
import warnings

import numpy as np

from ..base import MXNetError
from ..kvstore.base import KVStoreBase
from ..ndarray.ndarray import NDArray
from ..ndarray import sparse as _sp


# ---------------------------------------------------------------------------
# wire protocol: MAGIC | ver u8 | cmd u8 | nfields u8 | fields
# field := tag u8 | payload
#   'S' string:  u32 len | utf8
#   'B' bytes:   u32 len | raw
#   'J' json:    u32 len | utf8(json)
#   'F' float64: f64
#   'T' tensor:  u8 dlen | dtype-ascii | u8 ndim | i64*ndim dims | u64 | raw
# ---------------------------------------------------------------------------

_MAGIC = b"MXKV"
_VERSION = 1

CMD_OK = 0
CMD_INIT = 1
CMD_PUSH = 2
CMD_PULL = 3
CMD_ROW_SPARSE_PULL = 4
CMD_BARRIER = 5
CMD_SET_OPTIMIZER = 6
CMD_STOP = 7
CMD_HELLO = 8
CMD_PROFILER = 9
CMD_ERR = 255

_MAX_FRAME = 1 << 34  # 16 GiB sanity ceiling per tensor/string


def _wire_timeout():
    """Deadline (seconds) for any single blocking wire read/connect.

    A wedged peer (e.g. a server process that died mid-round, or one
    stuck in accelerator backend init) must surface as a clear error,
    never an indefinite ``recv`` hang.  0 disables (not recommended).

    The default is generous (30 min) because sync-mode replies
    legitimately block on the SLOWEST worker in the round — which may be
    spending many minutes in its first-step XLA compile — and a deadline
    that fires on a healthy straggler would kill the whole job.
    """
    t = float(os.environ.get("MXNET_KVSTORE_TIMEOUT", "1800"))
    return t if t > 0 else None


def _tune_socket(sock):
    """Per-connection transport tuning: no Nagle (tiny control frames
    must not wait behind tensor payloads) and multi-MB kernel buffers —
    gradient pushes move tens of MB per frame, and the ~200 KiB Linux
    defaults cap loopback/DCN throughput well below link speed
    (measured: 8 MiB buffers took the loopback push+pull round trip
    from ~0.6 to well over 1 GB/s)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8 << 20)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8 << 20)
    except OSError:
        pass  # transport tuning is best-effort, never fatal


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            raise MXNetError(
                "kvstore: peer unresponsive for %ss (MXNET_KVSTORE_TIMEOUT;"
                " a server or worker process is wedged or dead)"
                % sock.gettimeout())
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send(sock, cmd, *fields):
    """Encode small parts into one header buffer; large tensor payloads
    are sent as zero-copy memoryviews (no 64MB tobytes round trips)."""
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<BBB", _VERSION, cmd, len(fields))

    def flush():
        if out:
            sock.sendall(out)
            out.clear()

    for v in fields:
        if isinstance(v, str):
            b = v.encode()
            out += b"S" + struct.pack("<I", len(b)) + b
        elif isinstance(v, (bytes, bytearray)):
            out += b"B" + struct.pack("<I", len(v)) + bytes(v)
        elif isinstance(v, float):
            out += b"F" + struct.pack("<d", v)
        elif isinstance(v, dict):
            b = json.dumps(v).encode()
            out += b"J" + struct.pack("<I", len(b)) + b
        elif isinstance(v, np.ndarray):
            # asarray(order="C") keeps 0-d shapes; ascontiguousarray
            # would promote () to (1,)
            v = np.asarray(v, order="C")
            out += b"T" + struct.pack("<B", len(str(v.dtype))) \
                + str(v.dtype).encode() \
                + struct.pack("<B", v.ndim) \
                + struct.pack("<%dq" % v.ndim, *v.shape) \
                + struct.pack("<Q", v.nbytes)
            flush()
            sock.sendall(memoryview(v).cast("B"))
        else:
            raise MXNetError("wire: cannot encode %r" % type(v).__name__)
    flush()


def _recv(sock, max_bytes=_MAX_FRAME):
    """Decode one frame.  ``max_bytes`` caps any single field allocation —
    servers keep it tiny until the peer has authenticated, so an
    unauthenticated connection cannot force multi-GiB allocations."""
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        raise MXNetError("wire: bad magic %r" % magic)
    ver, cmd, nfields = struct.unpack("<BBB", _recv_exact(sock, 3))
    if ver != _VERSION:
        raise MXNetError("wire: version %d (want %d)" % (ver, _VERSION))
    fields = []
    for _ in range(nfields):
        tag = _recv_exact(sock, 1)
        if tag in (b"S", b"B", b"J"):
            (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
            if ln > max_bytes:
                raise MXNetError("wire: oversized field")
            raw = _recv_exact(sock, ln)
            if tag == b"S":
                fields.append(raw.decode())
            elif tag == b"J":
                fields.append(json.loads(raw.decode()))
            else:
                fields.append(raw)
        elif tag == b"F":
            fields.append(struct.unpack("<d", _recv_exact(sock, 8))[0])
        elif tag == b"T":
            (dlen,) = struct.unpack("<B", _recv_exact(sock, 1))
            dtype = np.dtype(_recv_exact(sock, dlen).decode())
            (ndim,) = struct.unpack("<B", _recv_exact(sock, 1))
            dims = struct.unpack("<%dq" % ndim, _recv_exact(sock, 8 * ndim)) \
                if ndim else ()
            (nbytes,) = struct.unpack("<Q", _recv_exact(sock, 8))
            expect = int(np.prod(dims, dtype=np.int64)) * dtype.itemsize \
                if ndim else dtype.itemsize
            if nbytes != expect or nbytes > max_bytes:
                raise MXNetError("wire: tensor size mismatch")
            arr = np.empty(dims, dtype)
            view = memoryview(arr).cast("B")
            got = 0
            while got < nbytes:
                try:
                    r = sock.recv_into(view[got:], nbytes - got)
                except socket.timeout:
                    raise MXNetError(
                        "kvstore: peer unresponsive mid-tensor for %ss "
                        "(MXNET_KVSTORE_TIMEOUT)" % sock.gettimeout())
                if not r:
                    raise ConnectionError("peer closed")
                got += r
            fields.append(arr)
        else:
            raise MXNetError("wire: unknown field tag %r" % tag)
    return cmd, fields


# -- shared-secret handshake -------------------------------------------------

def _secret():
    return os.environ.get("MXNET_KVSTORE_SECRET", "")


_warned_no_secret = []


def _auth_digest(secret, nonce, role):
    return _hmac.new(secret.encode(), nonce + role, hashlib.sha256) \
        .digest()


def _client_handshake(sock):
    """Mutual challenge-response (replay-proof: each side proves the
    secret over the OTHER side's fresh nonce).

    client -> HELLO [client_nonce]
    server -> OK    [server_nonce, HMAC(secret, client_nonce|"server")]
    client -> HELLO [HMAC(secret, server_nonce|"client")]
    server -> OK    []
    """
    secret = _secret()
    if not secret:
        if not _warned_no_secret:
            _warned_no_secret.append(True)
            warnings.warn(
                "MXNET_KVSTORE_SECRET unset: dist-kvstore connections are "
                "unauthenticated (tools/launch.py generates one per job)")
        return
    nonce = _secrets.token_bytes(16)
    _send(sock, CMD_HELLO, nonce)
    cmd, fields = _recv(sock, max_bytes=4096)
    if cmd != CMD_OK or len(fields) != 2 or not _hmac.compare_digest(
            fields[1], _auth_digest(secret, nonce, b"server")):
        raise MXNetError("kvstore handshake failed (bad server secret)")
    server_nonce = bytes(fields[0])
    _send(sock, CMD_HELLO, _auth_digest(secret, server_nonce, b"client"))
    cmd, _f = _recv(sock, max_bytes=4096)
    if cmd != CMD_OK:
        raise MXNetError("kvstore handshake rejected")


def _server_hello(sock, fields):
    """Serve the two-round handshake; returns True iff authenticated."""
    secret = _secret()
    if not secret or len(fields) != 1:
        # no secret configured server-side: reply with an empty proof —
        # a secret-bearing client will reject it (configs disagree)
        _send(sock, CMD_OK, b"", b"")
        return not secret
    client_nonce = bytes(fields[0])
    server_nonce = _secrets.token_bytes(16)
    _send(sock, CMD_OK, server_nonce,
          _auth_digest(secret, client_nonce, b"server"))
    cmd, f2 = _recv(sock, max_bytes=4096)
    if cmd != CMD_HELLO or len(f2) != 1 or not _hmac.compare_digest(
            bytes(f2[0]), _auth_digest(secret, server_nonce, b"client")):
        _send(sock, CMD_ERR, "authentication failed")
        return False
    _send(sock, CMD_OK)
    return True


def _server_port(root_port, server_id):
    return int(root_port) + 1 + server_id


# -- optimizer config (replaces the reference's pickled-object command) ------

_JSONABLE = (int, float, str, bool, type(None))


_DROP = object()


def _optimizer_to_config(optimizer):
    if getattr(optimizer, "lr_scheduler", None) is not None:
        raise MXNetError(
            "server-side optimizer with an lr_scheduler is not "
            "serializable over the wire; schedule worker-side instead")
    def scalar(x):
        if isinstance(x, _JSONABLE):
            return x
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.bool_):
            return bool(x)
        return _DROP

    state, dropped = {}, []
    for k, v in vars(optimizer).items():
        sv = scalar(v)
        if sv is not _DROP:
            state[k] = sv
            continue
        if isinstance(v, dict):
            items = [[kk, scalar(vv)] for kk, vv in v.items()
                     if isinstance(kk, (int, str))]
            if len(items) == len(v) and all(
                    vv is not _DROP for _, vv in items):
                # item-list form: JSON object keys are always strings,
                # which would corrupt int-keyed idx2name/lr_mult tables
                state[k] = {"__items__": items}
                continue
        dropped.append(k)
    if dropped:
        warnings.warn(
            "set_optimizer: attributes %s are not wire-serializable and "
            "were dropped; the server-side optimizer uses its defaults "
            "for them" % dropped)
    return {"class": type(optimizer).__name__.lower(), "state": state}


def _optimizer_from_config(cfg):
    from .. import optimizer as opt_mod

    opt = opt_mod.create(cfg["class"])
    for k, v in cfg.get("state", {}).items():
        if isinstance(v, dict) and "__items__" in v:
            v = {kk if not isinstance(kk, list) else tuple(kk): vv
                 for kk, vv in v["__items__"]}
        setattr(opt, k, v)
    return opt


# ---------------------------------------------------------------------------
# gradient compression (2-bit with error feedback)
# ---------------------------------------------------------------------------

class GradientCompression:
    """2-bit quantization with residual (parity: gradient_compression.h).

    Values are mapped to {-threshold, 0, +threshold}; the quantization
    error accumulates in a per-key residual added to the next gradient
    (error feedback), so compression bias vanishes over steps.
    """

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, arr):
        t = self.threshold
        r = self._residual.get(key)
        g = arr + (r if r is not None else 0.0)
        codes = np.zeros(g.shape, np.int8)
        codes[g >= t] = 1
        codes[g <= -t] = -1
        self._residual[key] = g - codes.astype(g.dtype) * t
        return codes

    def decompress(self, codes, dtype=np.float32):
        return codes.astype(dtype) * self.threshold


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _KeyState:
    __slots__ = ("value", "pending", "round", "round_done", "lock")

    def __init__(self):
        self.value = None
        self.pending = []  # accumulated pushes this round
        self.round = 0
        self.round_done = threading.Condition()
        self.lock = threading.Lock()


class DistServer:
    """One parameter-server process (parity: KVStoreDistServer).

    Sync mode: pushes for a key buffer until every worker contributed,
    then the merged gradient is applied (optimizer if set, else
    overwrite-with-sum) and all blocked pushers are released — the
    reference's barrier-per-key (``ApplyUpdates:346-349``).
    Async mode: every push applies immediately.
    """

    def __init__(self, port, num_workers, sync=True):
        self._port = int(port)
        self._num_workers = int(num_workers)
        self._sync = sync
        self._keys = {}
        self._keys_lock = threading.Lock()
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._stop = threading.Event()
        self._stop_count = 0
        self._stopped_ranks = set()
        self._stop_lock = threading.Lock()

    def _key(self, k):
        with self._keys_lock:
            st = self._keys.get(k)
            if st is None:
                st = self._keys[k] = _KeyState()
            return st

    # Dense server state is HOST numpy: the server is a host process doing
    # memcpy/accumulate — wrapping values in NDArray forced a device_put on
    # every push and an asnumpy on every pull (64MB copies each way; the
    # round-4 wire profile showed these, not framing, were the gap to the
    # raw-loopback floor).  The server-side-optimizer path still runs on
    # NDArray (it computes real updates).

    @staticmethod
    def _as_server_nd(v):
        return v if isinstance(v, (NDArray, _sp.RowSparseNDArray)) \
            else NDArray(v)

    def _apply(self, st, key, merged):
        if self._updater is not None:
            idx = int(key) if str(key).isdigit() else key
            st.value = self._as_server_nd(st.value)
            self._updater(idx, self._as_server_nd(merged), st.value)
        elif isinstance(merged, _sp.RowSparseNDArray):
            base = self._as_server_nd(st.value)
            base._set_data(merged.scatter_add_into(base.data() * 0))
            st.value = base
        elif isinstance(st.value, np.ndarray):
            st.value = np.asarray(merged, dtype=st.value.dtype)
        else:
            import jax.numpy as jnp

            st.value._set_data(jnp.asarray(merged, dtype=st.value.dtype))

    def _merge(self, pushes):
        first = pushes[0]
        if isinstance(first, _sp.RowSparseNDArray):
            acc = first
            for p in pushes[1:]:
                acc = acc + p
            return acc.compact()
        if len(pushes) == 1:
            return first
        # out-of-place first add (the recv buffer aliases push[0]),
        # in-place accumulation after
        acc = pushes[0] + pushes[1]
        for p in pushes[2:]:
            np.add(acc, p, out=acc)
        return acc

    @staticmethod
    def _prof_now():
        from .. import profiler as _prof

        return _prof._now_us()

    @staticmethod
    def _prof_span(name, t0):
        from .. import profiler as _prof

        _prof.add_span(name, t0, _prof._now_us(), cat="kvstore")

    def _handle(self, sock):
        authed = not _secret()
        # unauthenticated peers get a short deadline (can't park a server
        # thread); once authenticated the connection may legitimately sit
        # idle between training rounds, so the deadline comes off
        sock.settimeout(30.0 if _secret() else None)
        try:
            while not self._stop.is_set():
                # unauthenticated peers may only send tiny (HELLO) frames
                cmd, f = _recv(
                    sock, max_bytes=_MAX_FRAME if authed else 4096)
                if cmd == CMD_HELLO:
                    authed = _server_hello(sock, f)
                    if not authed:
                        return
                    sock.settimeout(None)
                    continue
                if not authed:
                    _send(sock, CMD_ERR, "unauthenticated")
                    return
                if cmd == CMD_INIT:
                    key, value = f
                    st = self._key(key)
                    with st.lock:
                        if st.value is None:
                            st.value = np.asarray(value)
                    _send(sock, CMD_OK)
                elif cmd == CMD_PUSH:
                    t0 = self._prof_now()
                    key = f[0]
                    self._do_push(key, self._decode(f[1], f[2:]))
                    _send(sock, CMD_OK)
                    self._prof_span("KVStoreServer::push", t0)
                elif cmd == CMD_PULL:
                    t0 = self._prof_now()
                    (key,) = f
                    st = self._key(key)
                    with st.lock:
                        # server wire send needs host bytes
                        val = st.value if isinstance(st.value, np.ndarray) \
                            else st.value.asnumpy()  # mxlint: allow-host-sync
                    _send(sock, CMD_OK, val)
                    self._prof_span("KVStoreServer::pull", t0)
                elif cmd == CMD_ROW_SPARSE_PULL:
                    key, row_ids = f
                    st = self._key(key)
                    with st.lock:
                        # server wire send needs host bytes
                        base = st.value if isinstance(st.value, np.ndarray) \
                            else st.value.asnumpy()  # mxlint: allow-host-sync
                        rows = base[np.asarray(row_ids)]
                    _send(sock, CMD_OK, rows)
                elif cmd == CMD_BARRIER:
                    self._do_barrier()
                    _send(sock, CMD_OK)
                elif cmd == CMD_SET_OPTIMIZER:
                    from .. import optimizer as opt_mod

                    self._optimizer = _optimizer_from_config(f[0])
                    self._updater = opt_mod.get_updater(self._optimizer)
                    _send(sock, CMD_OK)
                elif cmd == CMD_PROFILER:
                    # remote profiling (parity: the reference's
                    # kSetProfilerParams server command,
                    # include/mxnet/kvstore.h:49 +
                    # tests/nightly/test_server_profiling.py)
                    from .. import profiler as _prof

                    cfg = f[0]
                    action = cfg.get("action")
                    try:
                        if action == "set_state":
                            _prof.set_state(cfg.get("state", "stop"))
                            _send(sock, CMD_OK, "")
                        elif action == "set_config":
                            _prof.set_config(**cfg.get("config", {}))
                            _send(sock, CMD_OK, "")
                        elif action == "dump":
                            _prof.dump(finished=bool(cfg.get("finished",
                                                             True)))
                            _send(sock, CMD_OK, "")
                        elif action == "dumps":
                            _send(sock, CMD_OK,
                                  _prof.dumps(
                                      reset=bool(cfg.get("reset"))))
                        else:
                            _send(sock, CMD_ERR,
                                  "unknown profiler action %r" % (action,))
                    except Exception as pe:  # noqa: BLE001
                        # a bad config key / unwritable dump path must
                        # NOT kill the connection training runs on —
                        # report it and keep serving
                        _send(sock, CMD_ERR,
                              "profiler %s failed: %s" % (action, pe))
                elif cmd == CMD_STOP:
                    _send(sock, CMD_OK)
                    # the server dies only when EVERY distinct worker
                    # rank said stop (ps-lite Finalize semantics): under
                    # load, worker finish times skew by many seconds —
                    # the first finisher must not kill the service under
                    # the rest.  Duplicate stops from one rank (retry,
                    # second DistKVStore instance) don't count twice; a
                    # rankless STOP (legacy frame) falls back to a
                    # counter.
                    with self._stop_lock:
                        if f:
                            self._stopped_ranks.add(str(f[0]))
                            done = len(self._stopped_ranks) \
                                >= self._num_workers
                        else:
                            self._stop_count += 1
                            done = self._stop_count >= self._num_workers
                        if done:
                            self._stop.set()
                    return
                else:
                    _send(sock, CMD_ERR, "unknown command %r" % (cmd,))
        except (ConnectionError, OSError):
            pass
        except Exception:
            # malformed frame / handler bug: the stream may be out of
            # sync — log and drop the connection (client surfaces a
            # socket error rather than a blind timeout)
            import logging
            import traceback

            logging.getLogger(__name__).warning(
                "kvstore server connection dropped:\n%s",
                traceback.format_exc())

    @staticmethod
    def _decode(kind, fields):
        if kind == "dense":
            return fields[0]  # host numpy; stays host-side on the server
        if kind == "rsp":
            vals, idx, shape = fields
            return _sp.RowSparseNDArray(np.asarray(vals), np.asarray(idx),
                                        tuple(int(d) for d in shape))
        if kind == "2bit":
            codes, threshold = fields
            return codes.astype(np.float32) * threshold
        raise MXNetError("bad payload kind %r" % (kind,))

    def _do_push(self, key, value):
        st = self._key(key)
        if not self._sync:
            with st.lock:
                self._apply(st, key, value)
            return
        with st.round_done:
            st.pending.append(value)
            if len(st.pending) == self._num_workers:
                merged = self._merge(st.pending)
                with st.lock:
                    self._apply(st, key, merged)
                st.pending = []
                st.round += 1
                st.round_done.notify_all()
            else:
                gen = st.round
                while st.round == gen:
                    st.round_done.wait(timeout=60)

    def _do_barrier(self):
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count == self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
            else:
                while self._barrier_gen == gen:
                    self._barrier_cv.wait(timeout=60)

    def run(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # all interfaces: workers on OTHER hosts reach this server via
        # DMLC_PS_ROOT_URI (loopback-only would break true multi-host)
        srv.bind(("", self._port))
        srv.listen(64)
        srv.settimeout(1.0)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
                _tune_socket(conn)
            except socket.timeout:
                continue
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        srv.close()


# ---------------------------------------------------------------------------
# worker-side store
# ---------------------------------------------------------------------------

class DistKVStore(KVStoreBase):
    """Worker-side distributed store (parity: KVStoreDist).

    Types: ``dist_sync`` / ``dist_device_sync`` (barrier-per-key sync,
    identical here — device vs cpu reduce location is moot on TPU) and
    ``dist_async`` (server applies pushes immediately).
    """

    def __init__(self, name="dist_sync"):
        self._type = name
        self._sync = "async" not in name
        self._rank = int(os.environ.get("DMLC_RANK",
                                        os.environ.get("DMLC_WORKER_ID", "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        self._root = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self._root_port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._socks = {}
        self._lock = threading.Lock()
        self._gc = None
        self._optimizer = None
        # keys this worker has init()ed — every worker runs the same init
        # sequence, so the local schema mirrors the cluster's and push/
        # pull key sets can be validated BEFORE any RPC (CC605)
        self._key_schema = set()

    # -- plumbing ----------------------------------------------------------
    def _shard(self, key):
        """Key → server id (parity: EncodeDefaultKey sharding).

        Deterministic across processes (Python's hash() is salted per
        process and would send the same key to different servers from
        different workers, deadlocking the sync barrier).
        """
        import zlib

        k = str(key)
        if k.isdigit():
            return int(k) % self._num_servers
        return zlib.crc32(k.encode()) % self._num_servers

    def _sock(self, server_id):
        with self._lock:
            s = self._socks.get(server_id)
            if s is None:
                addr = (self._root,
                        _server_port(self._root_port, server_id))
                # retry refused connects: at job start the server process
                # may still be importing/binding (ps-lite retries the van
                # connect the same way).  The connect phase gets its OWN
                # short deadline — the wire-read timeout is sized for
                # sync-round reads waiting on slow compiles (30min); a dead
                # or misaddressed server must fail in seconds, not that
                import time as _time

                deadline = _time.monotonic() + min(
                    _wire_timeout() or 60, 60)
                while True:
                    try:
                        s = socket.create_connection(addr, timeout=60)
                        break
                    except (ConnectionRefusedError, socket.timeout,
                            OSError):
                        if _time.monotonic() >= deadline:
                            raise
                        _time.sleep(0.2)
                _tune_socket(s)
                # every later read inherits the wire deadline: a wedged
                # server raises a diagnosable MXNetError instead of
                # blocking this worker forever
                s.settimeout(_wire_timeout())
                _client_handshake(s)
                self._socks[server_id] = s
            return s

    def _rpc(self, key, cmd, *fields):
        s = self._sock(self._shard(key))
        with self._lock:
            _send(s, cmd, *fields)
            rcmd, rfields = _recv(s)
        if rcmd != CMD_OK:
            raise MXNetError("kvstore rpc failed: %r" % (rfields,))
        return rfields[0] if rfields else None

    # -- remote (server-side) profiling ------------------------------------
    def _profiler_broadcast(self, cfg):
        """Send one profiler command to EVERY server; returns replies in
        server-id order (parity: kSetProfilerParams,
        include/mxnet/kvstore.h:49)."""
        outs = []
        for sid in range(self._num_servers):
            s = self._sock(sid)
            with self._lock:
                _send(s, CMD_PROFILER, cfg)
                rcmd, rfields = _recv(s)
            if rcmd != CMD_OK:
                raise MXNetError("server profiler command failed: %r"
                                 % (rfields,))
            outs.append(rfields[0] if rfields else "")
        return outs

    def set_server_profiler_state(self, state):
        """Start/stop the profiler inside every server process."""
        self._profiler_broadcast({"action": "set_state", "state": state})

    def set_server_profiler_config(self, **config):
        self._profiler_broadcast({"action": "set_config",
                                  "config": config})

    def server_profiler_dump(self, finished=True):
        """Every server writes its own chrome-trace file server-side."""
        self._profiler_broadcast({"action": "dump", "finished": finished})

    def server_profiler_dumps(self, reset=False):
        """Fetch each server's aggregate per-op stats table (one string
        per server)."""
        return self._profiler_broadcast({"action": "dumps",
                                         "reset": reset})

    # -- KVStore API -------------------------------------------------------
    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER,)

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    size = num_workers

    def set_gradient_compression(self, compression_params):
        if compression_params.get("type") != "2bit":
            raise MXNetError("only 2bit compression is supported")
        self._gc = GradientCompression(
            compression_params.get("threshold", 0.5))

    def _check_keys(self, op, keys):
        """CC605 pre-dispatch validation: duplicate keys in one call, or
        push/pull keys outside the init()ed schema, deadlock sync mode
        (the server barriers per key counting ONE contribution per worker
        per round) — fail here, before any bytes hit the wire."""
        ks = [str(k) for k in keys]
        dups = sorted({k for k in ks if ks.count(k) > 1})
        if dups:
            raise MXNetError(
                "CC605 (kvstore-key-divergence): duplicate key(s) %s in "
                "one %s call — sync mode counts one contribution per "
                "worker per key per round, so a double push wedges the "
                "round" % (dups, op))
        if op != "init" and self._key_schema:
            unknown = sorted(set(ks) - self._key_schema)
            if unknown:
                raise MXNetError(
                    "CC605 (kvstore-key-divergence): %s of key(s) %s not "
                    "in the initialized schema %s — workers must init() "
                    "every key on every worker first, or divergent key "
                    "sets deadlock the sync round"
                    % (op, unknown, sorted(self._key_schema)))

    def init(self, key, value):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        values = [value] if not isinstance(key, (list, tuple)) else value
        self._check_keys("init", keys)
        self._key_schema.update(str(k) for k in keys)
        for k, v in zip(keys, values):
            if self._rank == 0:
                # init ships host bytes over the wire  # mxlint: allow-host-sync
                arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
                self._rpc(k, CMD_INIT, str(k), arr)
        self.barrier()

    def _encode(self, key, v):
        """(kind, *wire_fields) for a pushed value."""
        if isinstance(v, _sp.RowSparseNDArray):
            return ("rsp", v.values.asnumpy(), v.indices.asnumpy(),
                    np.asarray(v.shape, np.int64))
        arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        if self._gc is not None:
            codes = self._gc.compress(str(key), arr)
            return ("2bit", codes, float(self._gc.threshold))
        return ("dense", arr)

    def _local_merge(self, value):
        vals = value if isinstance(value, (list, tuple)) else [value]
        if len(vals) == 1:
            return vals[0]
        if isinstance(vals[0], _sp.RowSparseNDArray):
            acc = vals[0]
            for v in vals[1:]:
                acc = acc + v
            return acc.compact()
        acc = vals[0].data()
        for v in vals[1:]:
            acc = acc + v.data()
        return NDArray(acc)

    def push(self, key, value, priority=0):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        values = [value] if not isinstance(key, (list, tuple)) else value
        self._check_keys("push", keys)
        for k, v in zip(keys, values):
            merged = self._local_merge(v)
            kind, *fields = self._encode(k, merged)
            self._rpc(k, CMD_PUSH, str(k), kind, *fields)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        outs = [out] if not isinstance(key, (list, tuple)) else out
        self._check_keys("pull", keys)
        for k, o in zip(keys, outs):
            val = self._rpc(k, CMD_PULL, str(k))
            dsts = o if isinstance(o, (list, tuple)) else [o]
            for dst in dsts:
                # copy=False: a dtype-matching pull (the common case)
                # must not clone 10s-of-MB gradients a second time
                dst._set_data(np.asarray(val).astype(dst.dtype,
                                                     copy=False))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if row_ids is None:
            return self.pull(key, out, priority)
        self._check_keys("row_sparse_pull", [key])
        rows_np = row_ids.asnumpy().astype(np.int64) \
            if hasattr(row_ids, "asnumpy") else np.asarray(row_ids,
                                                           np.int64)
        rows = self._rpc(key, CMD_ROW_SPARSE_PULL, str(key),
                         rows_np)
        dsts = out if isinstance(out, (list, tuple)) else [out]
        for dst in dsts:
            import jax.numpy as jnp

            full = jnp.zeros(dst.shape, dst.dtype).at[
                jnp.asarray(rows_np)].set(jnp.asarray(rows).astype(dst.dtype))
            dst._set_data(full)

    def barrier(self):
        # every worker must hit every server for a true global barrier
        for sid in range(self._num_servers):
            s = self._sock(sid)
            with self._lock:
                _send(s, CMD_BARRIER)
                rcmd, _f = _recv(s)
            if rcmd != CMD_OK:
                raise MXNetError("barrier failed")

    def set_optimizer(self, optimizer):
        """Run the optimizer server-side (parity: SendCommandToServers)."""
        self._optimizer = optimizer
        if self._rank == 0:
            cfg = _optimizer_to_config(optimizer)
            for sid in range(self._num_servers):
                s = self._sock(sid)
                with self._lock:
                    _send(s, CMD_SET_OPTIMIZER, cfg)
                    rcmd, _f = _recv(s)
                if rcmd != CMD_OK:
                    raise MXNetError("set_optimizer failed")
        self.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise MXNetError("server-side optimizer states live on the server")

    def load_optimizer_states(self, fname):
        raise MXNetError("server-side optimizer states live on the server")

    def stop(self):
        # EVERY server shard gets this worker's stop (even ones this
        # worker never pushed to): the server quits once each distinct
        # rank has said goodbye
        for sid in range(self._num_servers):
            try:
                s = self._sock(sid)
                with self._lock:
                    _send(s, CMD_STOP, str(self._rank))
                    _recv(s)
                s.close()
            except OSError:
                pass
        self._socks.clear()
