"""Distributed KVStore: multi-host parameter service over TCP (DCN path).

Capability parity with the reference's ps-lite stack:
``KVStoreDist`` (``src/kvstore/kvstore_dist.h:44``, worker side),
``KVStoreDistServer`` (``src/kvstore/kvstore_dist_server.h:155``, server
side: ``DataHandleEx:325``, sync aggregation ``ApplyUpdates:346`` that
waits for all workers per key, async immediate-apply mode, server-side
optimizer execution), key sharding across servers (``EncodeDefaultKey:263``),
row-sparse pulls (``:344-373``), and 2-bit gradient compression with
error-feedback residual (``gradient_compression.h:43-130``).

TPU-native stance: *intra-host* reduction rides ICI inside compiled
executables (``parallel.JitTrainStep`` psum) — this module is the
*inter-host* (DCN) tier, where the reference used ZMQ.  The wire is a
TYPED binary protocol over TCP (the shape of ps-lite's message format,
``kvstore_dist.h:267-327``): every frame is a magic+version+command
header followed by tagged fields (string / raw-tensor / float64 / json
/ bytes) — never pickled objects, so a hostile peer can inject data at
worst, not code.  Connections open with a shared-secret HMAC handshake
(``MXNET_KVSTORE_SECRET`` env, set by ``tools/launch.py``); the
scheduler rendezvous of ps-lite collapses into the servers themselves
(workers connect straight to the server addresses derived from the root
URI) — one fewer process with identical observable semantics.

Server-side optimizers travel as a JSON config (registry name +
scalar hyperparameters), not a code object; optimizers carrying an
``lr_scheduler`` must schedule worker-side (documented limitation —
the reference shipped the whole pickled object, an RCE by design).

Fault tolerance (``docs/fault_tolerance.md``): wire protocol v3 carries a
``{rank, seq, epoch}`` header on every *mutating* command (init/push/
barrier/set-optimizer/stop) — the per-worker monotonic sequence number
lets the server deduplicate replays, so the client can retry any failed
RPC with capped exponential backoff (``MXNET_KVSTORE_RETRIES`` ×
``MXNET_KVSTORE_BACKOFF``), evicting the dead socket, reconnecting,
re-handshaking and replaying the in-flight request; the server applies
each mutation exactly once (pulls are idempotent and retry freely).
Sync rounds and barriers carry a hard deadline
(``MXNET_KVSTORE_BARRIER_TIMEOUT``) after which the server *names the
missing ranks* in an error reply instead of wedging every worker —
optionally (``MXNET_KVSTORE_ALLOW_DEGRADED=1`` or
``MXNET_KVSTORE_EVICT_ON_TIMEOUT=1``) EVICTING them from the membership
roster and continuing with the survivors.

Elastic membership (wire v3): the server versions its rank roster with a
monotonic *membership epoch*.  Every mutating request carries the
sender's last-known epoch; a stale one is fenced with a typed ``CMD_ERR``
(``{"code": "stale_epoch", epoch, roster, step}``) that the client
answers by re-syncing its epoch and re-sending the SAME request —
fencing happens before the seq-dedup claim, so the re-send still dedups
against an already-applied original.  Deadline expiry evicts the missing
ranks and bumps the epoch (the fence is how survivors learn the new
roster); a recovered or new worker re-enters with ``CMD_JOIN``, admitted
at the next round boundary (``MXNET_ELASTIC_JOIN_TIMEOUT``) with its
stale seq cache cleared.  Every transition lands in the flight recorder
as a ``membership.*`` event.  All of it is exercised by the seeded
fault-injection harness (``mxnet_tpu.testing.faults``) hooked into
``_send``/``_recv``/``_sock``/``DistServer._handle``.

Environment (reference names, ``tools/launch.py`` sets them):
``DMLC_ROLE`` (worker|server|scheduler), ``DMLC_PS_ROOT_URI``,
``DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``, ``DMLC_NUM_SERVER``,
plus ``MXNET_KVSTORE_SECRET`` (optional shared secret).
"""
from __future__ import annotations

import collections
import hashlib
import hmac as _hmac
import json
import os
import random as _random
import secrets as _secrets
import socket
import struct
import threading
import time as _time
import warnings

import numpy as np

from ..base import MXNetError
from ..kvstore.base import KVStoreBase
from ..ndarray.ndarray import NDArray
from ..ndarray import sparse as _sp
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics
from ..testing.faults import maybe_inject as _inject, set_role as _set_role
from ..testing import lockcheck as _lockcheck
from ..testing import rescheck as _rescheck


# ---------------------------------------------------------------------------
# wire protocol v3: MAGIC | ver u8 | cmd u8 | nfields u8 | fields
# field := tag u8 | payload
#   'S' string:  u32 len | utf8
#   'B' bytes:   u32 len | raw
#   'J' json:    u32 len | utf8(json)
#   'F' float64: f64
#   'T' tensor:  u8 dlen | dtype-ascii | u8 ndim | i64*ndim dims | u64 | raw
# v2 (over v1): every mutating command's FIRST field is a 'J' meta dict
# {"rank": int, "seq": int} — the worker's monotonic sequence number the
# server dedups replayed mutations on (docs/fault_tolerance.md).
# v3 (over v2): the meta dict also carries "epoch" (the sender's
# last-known membership epoch; stale values are fenced with a typed
# CMD_ERR) and optionally "step" (training-step hint JOIN hands to
# re-admitted workers); new commands JOIN (re-admission at a round
# boundary) and EPOCH (roster/epoch/step query, non-mutating).
# ---------------------------------------------------------------------------

_MAGIC = b"MXKV"
_VERSION = 3

CMD_OK = 0
CMD_INIT = 1
CMD_PUSH = 2
CMD_PULL = 3
CMD_ROW_SPARSE_PULL = 4
CMD_BARRIER = 5
CMD_SET_OPTIMIZER = 6
CMD_STOP = 7
CMD_HELLO = 8
CMD_PROFILER = 9
CMD_JOIN = 10
CMD_EPOCH = 11
CMD_ERR = 255

# commands that change server state: these carry the {rank, seq} meta
# header and are dedup'd server-side (pulls retry freely without one)
_MUTATING = frozenset({CMD_INIT, CMD_PUSH, CMD_BARRIER, CMD_SET_OPTIMIZER,
                       CMD_STOP})

_MAX_FRAME = 1 << 34  # 16 GiB sanity ceiling per tensor/string

# human-readable command labels for metrics and trace spans
_CMD_NAMES = {
    CMD_OK: "ok", CMD_INIT: "init", CMD_PUSH: "push", CMD_PULL: "pull",
    CMD_ROW_SPARSE_PULL: "row_sparse_pull", CMD_BARRIER: "barrier",
    CMD_SET_OPTIMIZER: "set_optimizer", CMD_STOP: "stop",
    CMD_HELLO: "hello", CMD_PROFILER: "profiler", CMD_JOIN: "join",
    CMD_EPOCH: "epoch", CMD_ERR: "err",
}


def _retries():
    """Max RPC retries after the first attempt (MXNET_KVSTORE_RETRIES)."""
    return int(os.environ.get("MXNET_KVSTORE_RETRIES", "4"))


def _backoff():
    """Base backoff (s) for RPC retries; attempt k sleeps
    ``base * 2**k`` (capped at 5s) with ±25% jitter so reconnecting
    workers don't stampede the recovering server in lockstep."""
    return float(os.environ.get("MXNET_KVSTORE_BACKOFF", "0.2"))


def _backoff_sleep(attempt):
    base = _backoff()
    _time.sleep(min(base * (2 ** attempt), 5.0)
                * (0.75 + _random.random() * 0.5))


def _barrier_timeout():
    """Hard deadline (s) for a sync round / barrier wait on the SERVER
    (MXNET_KVSTORE_BARRIER_TIMEOUT).  When it expires the server replies
    with an error naming the missing ranks instead of wedging every
    worker forever.  0 disables (returns +inf)."""
    t = float(os.environ.get("MXNET_KVSTORE_BARRIER_TIMEOUT", "600"))
    return t if t > 0 else float("inf")


def _allow_degraded():
    """MXNET_KVSTORE_ALLOW_DEGRADED=1: on a round/barrier timeout, mark
    the missing ranks dead and continue with the survivors instead of
    erroring the round (dist_async jobs that prefer progress over
    completeness; dist_sync semantics become best-effort)."""
    return os.environ.get("MXNET_KVSTORE_ALLOW_DEGRADED", "0") \
        not in ("", "0")


def _evict_on_timeout():
    """MXNET_KVSTORE_EVICT_ON_TIMEOUT=1: deadline expiry on a sync round
    or barrier EVICTS the missing ranks — roster shrink + membership
    epoch bump, broadcast to survivors through the stale-epoch fence —
    and the survivors complete the round degraded *by design* (elastic
    training, docs/fault_tolerance.md).  The legacy ALLOW_DEGRADED knob
    now routes through the same eviction path; this is the
    elastic-training spelling."""
    return os.environ.get("MXNET_KVSTORE_EVICT_ON_TIMEOUT", "0") \
        not in ("", "0")


def _join_timeout():
    """Deadline (s) a JOIN waits for the next round boundary before the
    server refuses admission (MXNET_ELASTIC_JOIN_TIMEOUT).  A worker is
    only admitted BETWEEN rounds: admitting mid-round would change the
    contributor count under a round already armed for the old roster."""
    t = float(os.environ.get("MXNET_ELASTIC_JOIN_TIMEOUT", "60"))
    return t if t > 0 else float("inf")


def _wire_timeout():
    """Deadline (seconds) for any single blocking wire read/connect.

    A wedged peer (e.g. a server process that died mid-round, or one
    stuck in accelerator backend init) must surface as a clear error,
    never an indefinite ``recv`` hang.  0 disables (not recommended).

    The default is generous (30 min) because sync-mode replies
    legitimately block on the SLOWEST worker in the round — which may be
    spending many minutes in its first-step XLA compile — and a deadline
    that fires on a healthy straggler would kill the whole job.
    """
    t = float(os.environ.get("MXNET_KVSTORE_TIMEOUT", "1800"))
    return t if t > 0 else None


def _tune_socket(sock):
    """Per-connection transport tuning: no Nagle (tiny control frames
    must not wait behind tensor payloads) and multi-MB kernel buffers —
    gradient pushes move tens of MB per frame, and the ~200 KiB Linux
    defaults cap loopback/DCN throughput well below link speed
    (measured: 8 MiB buffers took the loopback push+pull round trip
    from ~0.6 to well over 1 GB/s)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8 << 20)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8 << 20)
    except OSError:
        pass  # transport tuning is best-effort, never fatal


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            raise MXNetError(
                "kvstore: peer unresponsive for %ss (MXNET_KVSTORE_TIMEOUT;"
                " a server or worker process is wedged or dead)"
                % sock.gettimeout())
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send(sock, cmd, *fields):
    """Encode small parts into one header buffer; large tensor payloads
    are sent as zero-copy memoryviews (no 64MB tobytes round trips)."""
    _inject("send", sock=sock, cmd=cmd)
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<BBB", _VERSION, cmd, len(fields))

    def flush():
        if out:
            sock.sendall(out)
            out.clear()

    for v in fields:
        if isinstance(v, str):
            b = v.encode()
            out += b"S" + struct.pack("<I", len(b)) + b
        elif isinstance(v, (bytes, bytearray)):
            out += b"B" + struct.pack("<I", len(v)) + bytes(v)
        elif isinstance(v, float):
            out += b"F" + struct.pack("<d", v)
        elif isinstance(v, dict):
            b = json.dumps(v).encode()
            out += b"J" + struct.pack("<I", len(b)) + b
        elif isinstance(v, np.ndarray):
            # asarray(order="C") keeps 0-d shapes; ascontiguousarray
            # would promote () to (1,)
            v = np.asarray(v, order="C")
            out += b"T" + struct.pack("<B", len(str(v.dtype))) \
                + str(v.dtype).encode() \
                + struct.pack("<B", v.ndim) \
                + struct.pack("<%dq" % v.ndim, *v.shape) \
                + struct.pack("<Q", v.nbytes)
            flush()
            sock.sendall(memoryview(v).cast("B"))
        else:
            raise MXNetError("wire: cannot encode %r" % type(v).__name__)
    flush()


def _recv(sock, max_bytes=_MAX_FRAME):
    """Decode one frame.  ``max_bytes`` caps any single field allocation —
    servers keep it tiny until the peer has authenticated, so an
    unauthenticated connection cannot force multi-GiB allocations."""
    _inject("recv", sock=sock)
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        raise MXNetError("wire: bad magic %r" % magic)
    ver, cmd, nfields = struct.unpack("<BBB", _recv_exact(sock, 3))
    if ver != _VERSION:
        raise MXNetError("wire: version %d (want %d)" % (ver, _VERSION))
    fields = []
    for _ in range(nfields):
        tag = _recv_exact(sock, 1)
        if tag in (b"S", b"B", b"J"):
            (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
            if ln > max_bytes:
                raise MXNetError("wire: oversized field")
            raw = _recv_exact(sock, ln)
            if tag == b"S":
                fields.append(raw.decode())
            elif tag == b"J":
                fields.append(json.loads(raw.decode()))
            else:
                fields.append(raw)
        elif tag == b"F":
            fields.append(struct.unpack("<d", _recv_exact(sock, 8))[0])
        elif tag == b"T":
            (dlen,) = struct.unpack("<B", _recv_exact(sock, 1))
            dtype = np.dtype(_recv_exact(sock, dlen).decode())
            (ndim,) = struct.unpack("<B", _recv_exact(sock, 1))
            dims = struct.unpack("<%dq" % ndim, _recv_exact(sock, 8 * ndim)) \
                if ndim else ()
            (nbytes,) = struct.unpack("<Q", _recv_exact(sock, 8))
            expect = int(np.prod(dims, dtype=np.int64)) * dtype.itemsize \
                if ndim else dtype.itemsize
            if nbytes != expect or nbytes > max_bytes:
                raise MXNetError("wire: tensor size mismatch")
            arr = np.empty(dims, dtype)
            view = memoryview(arr).cast("B")
            got = 0
            while got < nbytes:
                try:
                    r = sock.recv_into(view[got:], nbytes - got)
                except socket.timeout:
                    raise MXNetError(
                        "kvstore: peer unresponsive mid-tensor for %ss "
                        "(MXNET_KVSTORE_TIMEOUT)" % sock.gettimeout())
                if not r:
                    raise ConnectionError("peer closed")
                got += r
            fields.append(arr)
        else:
            raise MXNetError("wire: unknown field tag %r" % tag)
    return cmd, fields


# -- shared-secret handshake -------------------------------------------------

def _secret():
    return os.environ.get("MXNET_KVSTORE_SECRET", "")


_warned_no_secret = []


def _auth_digest(secret, nonce, role):
    return _hmac.new(secret.encode(), nonce + role, hashlib.sha256) \
        .digest()


def _client_handshake(sock):
    """Mutual challenge-response (replay-proof: each side proves the
    secret over the OTHER side's fresh nonce).

    client -> HELLO [client_nonce]
    server -> OK    [server_nonce, HMAC(secret, client_nonce|"server")]
    client -> HELLO [HMAC(secret, server_nonce|"client")]
    server -> OK    []
    """
    secret = _secret()
    if not secret:
        if not _warned_no_secret:
            _warned_no_secret.append(True)
            warnings.warn(
                "MXNET_KVSTORE_SECRET unset: dist-kvstore connections are "
                "unauthenticated (tools/launch.py generates one per job)")
        return
    nonce = _secrets.token_bytes(16)
    _send(sock, CMD_HELLO, nonce)
    cmd, fields = _recv(sock, max_bytes=4096)
    if cmd != CMD_OK or len(fields) != 2 or not _hmac.compare_digest(
            fields[1], _auth_digest(secret, nonce, b"server")):
        raise MXNetError("kvstore handshake failed (bad server secret)")
    server_nonce = bytes(fields[0])
    _send(sock, CMD_HELLO, _auth_digest(secret, server_nonce, b"client"))
    cmd, _f = _recv(sock, max_bytes=4096)
    if cmd != CMD_OK:
        raise MXNetError("kvstore handshake rejected")


def _server_hello(sock, fields):
    """Serve the two-round handshake; returns True iff authenticated."""
    secret = _secret()
    if not secret or len(fields) != 1:
        # no secret configured server-side: reply with an empty proof —
        # a secret-bearing client will reject it (configs disagree)
        _send(sock, CMD_OK, b"", b"")
        return not secret
    client_nonce = bytes(fields[0])
    server_nonce = _secrets.token_bytes(16)
    _send(sock, CMD_OK, server_nonce,
          _auth_digest(secret, client_nonce, b"server"))
    cmd, f2 = _recv(sock, max_bytes=4096)
    if cmd != CMD_HELLO or len(f2) != 1 or not _hmac.compare_digest(
            bytes(f2[0]), _auth_digest(secret, server_nonce, b"client")):
        _send(sock, CMD_ERR, "authentication failed")
        return False
    _send(sock, CMD_OK)
    return True


def _server_port(root_port, server_id):
    return int(root_port) + 1 + server_id


# -- optimizer config (replaces the reference's pickled-object command) ------

_JSONABLE = (int, float, str, bool, type(None))


_DROP = object()


def _optimizer_to_config(optimizer):
    if getattr(optimizer, "lr_scheduler", None) is not None:
        raise MXNetError(
            "server-side optimizer with an lr_scheduler is not "
            "serializable over the wire; schedule worker-side instead")
    def scalar(x):
        if isinstance(x, _JSONABLE):
            return x
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.bool_):
            return bool(x)
        return _DROP

    state, dropped = {}, []
    for k, v in vars(optimizer).items():
        sv = scalar(v)
        if sv is not _DROP:
            state[k] = sv
            continue
        if isinstance(v, dict):
            items = [[kk, scalar(vv)] for kk, vv in v.items()
                     if isinstance(kk, (int, str))]
            if len(items) == len(v) and all(
                    vv is not _DROP for _, vv in items):
                # item-list form: JSON object keys are always strings,
                # which would corrupt int-keyed idx2name/lr_mult tables
                state[k] = {"__items__": items}
                continue
        dropped.append(k)
    if dropped:
        warnings.warn(
            "set_optimizer: attributes %s are not wire-serializable and "
            "were dropped; the server-side optimizer uses its defaults "
            "for them" % dropped)
    return {"class": type(optimizer).__name__.lower(), "state": state}


def _optimizer_from_config(cfg):
    from .. import optimizer as opt_mod

    opt = opt_mod.create(cfg["class"])
    for k, v in cfg.get("state", {}).items():
        if isinstance(v, dict) and "__items__" in v:
            v = {kk if not isinstance(kk, list) else tuple(kk): vv
                 for kk, vv in v["__items__"]}
        setattr(opt, k, v)
    return opt


# ---------------------------------------------------------------------------
# gradient compression (2-bit with error feedback)
# ---------------------------------------------------------------------------

class GradientCompression:
    """2-bit quantization with residual (parity: gradient_compression.h).

    Values are mapped to {-threshold, 0, +threshold}; the quantization
    error accumulates in a per-key residual added to the next gradient
    (error feedback), so compression bias vanishes over steps.
    """

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, arr):
        t = self.threshold
        r = self._residual.get(key)
        g = arr + (r if r is not None else 0.0)
        codes = np.zeros(g.shape, np.int8)
        codes[g >= t] = 1
        codes[g <= -t] = -1
        self._residual[key] = g - codes.astype(g.dtype) * t
        return codes

    def decompress(self, codes, dtype=np.float32):
        return codes.astype(dtype) * self.threshold


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _KeyState:
    __slots__ = ("value", "pending", "contributors", "round", "round_done",
                 "last_error", "lock")

    def __init__(self):
        self.value = None
        self.pending = []  # accumulated pushes this round
        self.contributors = set()  # worker ranks that pushed this round
        self.round = 0
        self.round_done = _lockcheck.named_condition("kv.srv.round")
        self.last_error = None  # (generation, message) of a timed-out round
        self.lock = _lockcheck.named_lock("kv.srv.key")


class _RoundError(MXNetError):
    """A sync round / barrier expired its deadline; the message names the
    ranks that never contributed (docs/fault_tolerance.md)."""


class DistServer:
    """One parameter-server process (parity: KVStoreDistServer).

    Sync mode: pushes for a key buffer until every worker contributed,
    then the merged gradient is applied (optimizer if set, else
    overwrite-with-sum) and all blocked pushers are released — the
    reference's barrier-per-key (``ApplyUpdates:346-349``).
    Async mode: every push applies immediately.
    """

    # replies remembered per rank for sequence-number dedup; bounded —
    # a client holds at most a few RPCs in flight, so a replayed seq is
    # always among the most recent entries
    _SEQ_CACHE_DEPTH = 256

    def __init__(self, port, num_workers, sync=True):
        self._port = int(port)
        self._num_workers = int(num_workers)
        self._sync = sync
        self._keys = {}
        self._keys_lock = _lockcheck.named_lock("kv.srv.keys")
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0
        self._barrier_ranks = set()
        self._barrier_gen = 0
        self._barrier_error = None  # (generation, message)
        self._barrier_cv = _lockcheck.named_condition("kv.srv.barrier")
        self._stop = threading.Event()
        self._stop_count = 0
        self._stopped_ranks = set()
        self._stop_lock = _lockcheck.named_lock("kv.srv.stop")
        # fault-tolerance state (docs/fault_tolerance.md)
        self._seq_cache = {}  # rank -> OrderedDict(seq -> (cmd, fields))
        # guards + signals _seq_cache
        self._seq_cv = _lockcheck.named_condition("kv.srv.seq")
        self._dead_ranks = set()  # ranks evicted from the roster
        self._replays = 0  # dedup'd (replayed) mutations served from cache
        # elastic membership (wire v3): the roster is derived —
        # set(range(num_workers)) - dead_ranks — and versioned by a
        # monotonic epoch; every eviction/admission bumps it
        self._epoch = 0
        self._step = 0  # max training-step hint seen in mutating meta
        self._member_lock = _lockcheck.named_lock("kv.srv.member")
        self._last_rpc = {}  # rank -> (cmd name, seq) of its last mutation
        self._srv_sock = None
        self._conns = []
        self._member_gauges()

    # -- sequence-number dedup ---------------------------------------------
    def _seq_claim(self, rank, seq):
        """Atomically claim a sequence number at frame-decode time.

        Returns ``(False, None)`` for a first-seen seq (the caller must
        apply the mutation and ``_seq_store`` the reply), else
        ``(True, reply)`` — where ``reply`` is ``None`` while the
        ORIGINAL request is still mid-apply on another connection.
        Claiming before applying (not after) is what closes the race
        where a fast retry lands on a new connection while the first
        copy is still being applied: the replay must wait for the
        original's reply, never re-apply.
        """
        with self._seq_cv:
            cache = self._seq_cache.setdefault(rank,
                                               collections.OrderedDict())
            if seq in cache:
                self._replays += 1
                _metrics.counter(
                    "mxnet_kvstore_replay_hits_total",
                    help="replayed mutations answered from the dedup "
                         "cache without re-applying").inc()
                return True, cache[seq]
            cache[seq] = None  # claimed; apply in progress
            while len(cache) > self._SEQ_CACHE_DEPTH:
                cache.popitem(last=False)
            return False, None

    def _seq_store(self, rank, seq, reply):
        with self._seq_cv:
            cache = self._seq_cache.setdefault(rank,
                                               collections.OrderedDict())
            cache[seq] = reply
            self._seq_cv.notify_all()

    def _seq_await(self, rank, seq):
        """Block until the original request for ``seq`` stores its reply
        (returns it), or the deadline passes (returns ``None`` — the
        original handler died mid-apply and will never answer)."""
        deadline = _time.monotonic() + _barrier_timeout()
        with self._seq_cv:
            while True:
                reply = self._seq_cache.get(rank, {}).get(seq)
                if reply is not None:
                    return reply
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return None
                self._seq_cv.wait(timeout=min(remaining, 60.0))

    def _live_workers(self):
        return self._num_workers - len(self._dead_ranks)

    def _mark_dead(self, ranks):
        """Degraded mode: declare ranks dead so later rounds/barriers/stop
        count only the survivors."""
        self._dead_ranks.update(ranks)
        warnings.warn(
            "kvstore server: continuing degraded without rank(s) %s "
            "(%d/%d workers remain)" % (sorted(ranks),
                                        self._live_workers(),
                                        self._num_workers))

    # -- elastic membership (wire v3) --------------------------------------
    def _roster(self):
        return sorted(set(range(self._num_workers)) - self._dead_ranks)

    def _membership_info(self):
        """The dict a fence / JOIN / EPOCH reply carries."""
        return {"epoch": self._epoch, "roster": self._roster(),
                "step": self._step}

    def _member_gauges(self):
        _metrics.gauge(
            "mxnet_membership_epoch",
            help="membership epoch of this kvstore shard (bumps on every "
                 "eviction or admission)").set(self._epoch)
        _metrics.gauge(
            "mxnet_ranks_active",
            help="worker ranks currently in the membership roster"
        ).set(self._live_workers())

    def _evict_ranks(self, ranks, reason):
        """Evict ranks from the roster: mark dead, bump the membership
        epoch, and leave a forensic trail — one ``membership.evict``
        flight event per rank naming its LAST RPC (command + seq), so a
        post-mortem dump shows what the lost rank was doing when the
        deadline fired."""
        ranks = sorted({int(r) for r in ranks if r is not None}
                       - self._dead_ranks)
        if not ranks:
            return
        self._mark_dead(ranks)
        with self._member_lock:
            self._epoch += 1
            epoch = self._epoch
        for r in ranks:
            last_cmd, last_seq = self._last_rpc.get(r, ("", -1))
            _flight.record("membership.evict", rank=r, epoch=epoch,
                           reason=reason, last_rpc=last_cmd,
                           last_seq=last_seq)
            _metrics.counter(
                "mxnet_rank_evictions_total",
                help="worker ranks evicted from the membership roster",
                reason=reason).inc()
        _flight.record("membership.epoch", epoch=epoch, reason=reason,
                       ranks_active=self._live_workers())
        self._member_gauges()

    def _do_join(self, rank):
        """Admit (or re-admit) ``rank`` at the next round boundary.

        Blocks (poll, not wedge: MXNET_ELASTIC_JOIN_TIMEOUT) until no
        sync round or barrier is mid-flight, then shrinks ``_dead_ranks``
        (growing ``_num_workers`` for a genuinely new rank), bumps the
        epoch, and CLEARS the rank's seq-dedup cache — a re-admitted
        worker is a fresh incarnation restarting its sequence numbers at
        1, and the dead incarnation's cached replies must not answer it.
        Idempotent: joining while already in the roster changes nothing.
        """
        rank = int(rank)
        deadline = _time.monotonic() + _join_timeout()
        while not self._stop.is_set():
            with self._barrier_cv:
                mid_barrier = self._barrier_count > 0
            with self._keys_lock:
                states = list(self._keys.values())
            if not mid_barrier and not any(st.pending for st in states):
                break
            if _time.monotonic() >= deadline:
                raise _RoundError(
                    "join(rank %d): no round boundary within %gs "
                    "(MXNET_ELASTIC_JOIN_TIMEOUT) — a sync round or "
                    "barrier is still mid-flight" % (rank, _join_timeout()))
            _time.sleep(0.005)
        with self._member_lock:
            rejoin = rank in self._dead_ranks
            grew = rank >= self._num_workers
            self._dead_ranks.discard(rank)
            if grew:
                self._num_workers = rank + 1
            if rejoin or grew:
                self._epoch += 1
            with self._seq_cv:
                self._seq_cache.pop(rank, None)
            with self._stop_lock:
                self._stopped_ranks.discard(str(rank))
            info = self._membership_info()
        if rejoin or grew:
            _flight.record("membership.join", rank=rank,
                           epoch=info["epoch"], rejoin=rejoin,
                           ranks_active=self._live_workers())
            self._member_gauges()
        return info

    def _key(self, k):
        with self._keys_lock:
            st = self._keys.get(k)
            if st is None:
                st = self._keys[k] = _KeyState()
            return st

    # Dense server state is HOST numpy: the server is a host process doing
    # memcpy/accumulate — wrapping values in NDArray forced a device_put on
    # every push and an asnumpy on every pull (64MB copies each way; the
    # round-4 wire profile showed these, not framing, were the gap to the
    # raw-loopback floor).  The server-side-optimizer path still runs on
    # NDArray (it computes real updates).

    @staticmethod
    def _as_server_nd(v):
        return v if isinstance(v, (NDArray, _sp.RowSparseNDArray)) \
            else NDArray(v)

    def _apply(self, st, key, merged):
        if self._updater is not None:
            idx = int(key) if str(key).isdigit() else key
            st.value = self._as_server_nd(st.value)
            self._updater(idx, self._as_server_nd(merged), st.value)
        elif isinstance(merged, _sp.RowSparseNDArray):
            base = self._as_server_nd(st.value)
            base._set_data(merged.scatter_add_into(base.data() * 0))
            st.value = base
        elif isinstance(st.value, np.ndarray):
            st.value = np.asarray(merged, dtype=st.value.dtype)
        else:
            import jax.numpy as jnp

            st.value._set_data(jnp.asarray(merged, dtype=st.value.dtype))

    def _merge(self, pushes):
        first = pushes[0]
        if isinstance(first, _sp.RowSparseNDArray):
            acc = first
            for p in pushes[1:]:
                acc = acc + p
            return acc.compact()
        if len(pushes) == 1:
            return first
        # out-of-place first add (the recv buffer aliases push[0]),
        # in-place accumulation after
        acc = pushes[0] + pushes[1]
        for p in pushes[2:]:
            np.add(acc, p, out=acc)
        return acc

    @staticmethod
    def _prof_now():
        from .. import profiler as _prof

        return _prof._now_us()

    @staticmethod
    def _prof_span(name, t0, rank=None, span=None, command=None):
        """Record one handler span + its latency histogram.

        Spans land on trace pid ``rank + 1`` (the requesting worker's
        rank; pid 0 stays the local process) carrying the wire span id,
        so a merged trace shows this handler nested under the worker's
        ``kv_<command>`` RPC span that caused it."""
        from .. import profiler as _prof

        t1 = _prof._now_us()
        _prof.add_span(name, t0, t1, cat="kvstore",
                       pid=0 if rank is None else rank + 1,
                       args={"span": span} if span else None)
        if command is not None and _metrics.enabled():
            _metrics.histogram(
                "mxnet_kvstore_server_handle_seconds",
                help="server-side request handler wall time",
                command=command).observe((t1 - t0) / 1e6)

    def _handle(self, sock):
        authed = not _secret()
        _set_role("server")
        # unauthenticated peers get a short deadline (can't park a server
        # thread); once authenticated the connection may legitimately sit
        # idle between training rounds, so the deadline comes off
        sock.settimeout(30.0 if _secret() else None)
        try:
            while not self._stop.is_set():
                # unauthenticated peers may only send tiny (HELLO) frames
                cmd, f = _recv(
                    sock, max_bytes=_MAX_FRAME if authed else 4096)
                # record BEFORE the chaos hook: a kill_server injection
                # must leave the handled command in the flight ring
                _flight.record("kv.serve", cmd=_CMD_NAMES.get(cmd, str(cmd)))
                _inject("server_handle", server=self, cmd=cmd)
                if cmd == CMD_HELLO:
                    authed = _server_hello(sock, f)
                    if not authed:
                        return
                    sock.settimeout(None)
                    continue
                if not authed:
                    _send(sock, CMD_ERR, "unauthenticated")
                    return
                # mutating commands carry the {rank, seq} meta header:
                # a replayed sequence number is answered from the reply
                # cache WITHOUT re-applying (exactly-once mutations under
                # client retry; docs/fault_tolerance.md)
                rank = seq = span = None
                if cmd in _MUTATING and f and isinstance(f[0], dict) \
                        and "seq" in f[0]:
                    meta = f[0]
                    rank, seq = int(meta.get("rank", 0)), int(meta["seq"])
                    span = meta.get("span")  # trace correlation id
                    f = f[1:]
                    if "step" in meta:
                        self._step = max(self._step, int(meta["step"]))
                    self._last_rpc[rank] = (_CMD_NAMES.get(cmd, str(cmd)),
                                            seq)
                    # membership fencing (wire v3) — BEFORE the seq
                    # claim, so a fenced request re-sent with a fresh
                    # epoch and the SAME seq still dedups against an
                    # already-applied original
                    epoch = meta.get("epoch")
                    if epoch is not None and int(epoch) != self._epoch:
                        _send(sock, CMD_ERR,
                              dict(self._membership_info(),
                                   code="stale_epoch"))
                        continue
                    if rank in self._dead_ranks:
                        # an evicted rank must JOIN, not mutate: its
                        # contributions would corrupt survivor rounds
                        _send(sock, CMD_ERR,
                              dict(self._membership_info(),
                                   code="evicted", rank=rank))
                        continue
                    replay, cached = self._seq_claim(rank, seq)
                    if replay:
                        # the original may still be mid-apply on another
                        # connection: wait for ITS reply — re-applying
                        # here would break exactly-once
                        if cached is None:
                            cached = self._seq_await(rank, seq)
                        if cached is None:
                            _send(sock, CMD_ERR,
                                  "replayed request (rank %d seq %d) "
                                  "never completed server-side"
                                  % (rank, seq))
                        else:
                            _send(sock, cached[0], *cached[1])
                        if cmd == CMD_STOP:
                            return
                        continue

                def reply(rcmd, *rfields):
                    if seq is not None:
                        self._seq_store(rank, seq, (rcmd, rfields))
                    _send(sock, rcmd, *rfields)

                if cmd == CMD_INIT:
                    key, value = f
                    st = self._key(key)
                    with st.lock:
                        if st.value is None:
                            st.value = np.asarray(value)
                    reply(CMD_OK)
                elif cmd == CMD_PUSH:
                    t0 = self._prof_now()
                    key = f[0]
                    try:
                        self._do_push(key, self._decode(f[1], f[2:]), rank)
                        # span closes BEFORE the reply: the worker may
                        # tear the profiler down the moment its RPC
                        # returns, and nesting under the worker's
                        # kv_push span requires ending first anyway
                        self._prof_span("KVStoreServer::push", t0,
                                        rank=rank, span=span,
                                        command="push")
                        reply(CMD_OK)
                    except _RoundError as e:
                        reply(CMD_ERR, str(e))
                elif cmd == CMD_PULL:
                    t0 = self._prof_now()
                    (key,) = f
                    st = self._key(key)
                    with st.lock:
                        val = st.value
                    # server wire send needs host bytes; the pull runs
                    # AFTER the lock drops — a device sync under st.lock
                    # would stall every pusher to this key (CD1103)
                    if not isinstance(val, np.ndarray):
                        val = val.asnumpy()  # mxlint: allow-host-sync
                    self._prof_span("KVStoreServer::pull", t0,
                                    rank=rank, span=span, command="pull")
                    _send(sock, CMD_OK, val)
                elif cmd == CMD_ROW_SPARSE_PULL:
                    key, row_ids = f
                    st = self._key(key)
                    with st.lock:
                        base = st.value
                    # host pull + row gather outside the lock (CD1103):
                    # we gather from a consistent snapshot reference; a
                    # racing round replaces st.value wholesale, it never
                    # mutates the array we captured
                    if not isinstance(base, np.ndarray):
                        base = base.asnumpy()  # mxlint: allow-host-sync
                    rows = base[np.asarray(row_ids)]
                    _send(sock, CMD_OK, rows)
                elif cmd == CMD_BARRIER:
                    try:
                        self._do_barrier(rank)
                        reply(CMD_OK)
                    except _RoundError as e:
                        reply(CMD_ERR, str(e))
                elif cmd == CMD_SET_OPTIMIZER:
                    from .. import optimizer as opt_mod

                    self._optimizer = _optimizer_from_config(f[0])
                    self._updater = opt_mod.get_updater(self._optimizer)
                    reply(CMD_OK)
                elif cmd == CMD_JOIN:
                    # deliberately NOT in _MUTATING: a joining worker is
                    # a fresh incarnation whose seq numbers restart, so
                    # it cannot carry a dedup header — the operation is
                    # idempotent instead
                    try:
                        _send(sock, CMD_OK,
                              self._do_join(f[0].get("rank", 0)))
                    except _RoundError as e:
                        _send(sock, CMD_ERR, str(e))
                elif cmd == CMD_EPOCH:
                    _send(sock, CMD_OK, self._membership_info())
                elif cmd == CMD_PROFILER:
                    # remote profiling (parity: the reference's
                    # kSetProfilerParams server command,
                    # include/mxnet/kvstore.h:49 +
                    # tests/nightly/test_server_profiling.py)
                    from .. import profiler as _prof

                    cfg = f[0]
                    action = cfg.get("action")
                    try:
                        if action == "set_state":
                            _prof.set_state(cfg.get("state", "stop"))
                            _send(sock, CMD_OK, "")
                        elif action == "set_config":
                            _prof.set_config(**cfg.get("config", {}))
                            _send(sock, CMD_OK, "")
                        elif action == "pause":
                            _prof.pause()
                            _send(sock, CMD_OK, "")
                        elif action == "resume":
                            _prof.resume()
                            _send(sock, CMD_OK, "")
                        elif action == "dump":
                            _prof.dump(finished=bool(cfg.get("finished",
                                                             True)))
                            _send(sock, CMD_OK, "")
                        elif action == "dumps":
                            _send(sock, CMD_OK,
                                  _prof.dumps(
                                      reset=bool(cfg.get("reset"))))
                        else:
                            _send(sock, CMD_ERR,
                                  "unknown profiler action %r" % (action,))
                    except Exception as pe:  # noqa: BLE001
                        # a bad config key / unwritable dump path must
                        # NOT kill the connection training runs on —
                        # report it and keep serving
                        _send(sock, CMD_ERR,
                              "profiler %s failed: %s" % (action, pe))
                elif cmd == CMD_STOP:
                    reply(CMD_OK)
                    # the server dies only when EVERY distinct worker
                    # rank said stop (ps-lite Finalize semantics): under
                    # load, worker finish times skew by many seconds —
                    # the first finisher must not kill the service under
                    # the rest.  Duplicate stops from one rank (retry,
                    # second DistKVStore instance) don't count twice —
                    # the meta rank (or a legacy rank field) keys a set;
                    # a rankless STOP falls back to a counter.  Ranks
                    # declared dead by a degraded round count as stopped
                    # (they will never say goodbye).
                    stop_rank = str(rank) if rank is not None \
                        else (str(f[0]) if f else None)
                    with self._stop_lock:
                        if stop_rank is not None:
                            self._stopped_ranks.add(stop_rank)
                            done = len(self._stopped_ranks
                                       | {str(r)
                                          for r in self._dead_ranks}) \
                                >= self._num_workers
                        else:
                            self._stop_count += 1
                            done = self._stop_count >= self._live_workers()
                        if done:
                            self._stop.set()
                    return
                else:
                    _send(sock, CMD_ERR, "unknown command %r" % (cmd,))
        except (ConnectionError, OSError):
            pass
        except Exception:
            # malformed frame / handler bug: the stream may be out of
            # sync — log and drop the connection (client surfaces a
            # socket error rather than a blind timeout)
            import logging
            import traceback

            logging.getLogger(__name__).warning(
                "kvstore server connection dropped:\n%s",
                traceback.format_exc())

    @staticmethod
    def _decode(kind, fields):
        if kind == "dense":
            return fields[0]  # host numpy; stays host-side on the server
        if kind == "rsp":
            vals, idx, shape = fields
            return _sp.RowSparseNDArray(np.asarray(vals), np.asarray(idx),
                                        tuple(int(d) for d in shape))
        if kind == "2bit":
            codes, threshold = fields
            return codes.astype(np.float32) * threshold
        raise MXNetError("bad payload kind %r" % (kind,))

    def _missing_ranks(self, contributed):
        known = {int(r) for r in contributed if r is not None}
        return sorted(set(range(self._num_workers)) - known
                      - self._dead_ranks)

    def _complete_round(self, st, key):
        """Merge + apply the pending pushes and release the round.
        Caller holds ``st.round_done``."""
        merged = self._merge(st.pending)
        with st.lock:
            self._apply(st, key, merged)
        st.pending = []
        st.contributors = set()
        st.round += 1
        st.round_done.notify_all()

    def _do_push(self, key, value, rank=None):
        st = self._key(key)
        if not self._sync:
            with st.lock:
                self._apply(st, key, value)
            return
        with st.round_done:
            gen = st.round
            st.pending.append(value)
            st.contributors.add(rank)
            # release on DISTINCT live contributors, not raw push count:
            # a rankless (legacy) push falls back to counting entries
            arrived = len({r for r in st.contributors if r is not None}) \
                if rank is not None else len(st.pending)
            if arrived >= self._live_workers():
                self._complete_round(st, key)
                return
            # deadline loop (NOT a bare re-check wait: a dead worker must
            # surface as an error naming it, never a silent wedge — RB701)
            deadline = _time.monotonic() + _barrier_timeout()
            while st.round == gen:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    missing = self._missing_ranks(st.contributors)
                    msg = ("sync round for key %r timed out after %gs "
                           "(MXNET_KVSTORE_BARRIER_TIMEOUT) waiting on "
                           "rank(s) %s — %d/%d contributions arrived"
                           % (key, _barrier_timeout(), missing,
                              len(st.pending), self._live_workers()))
                    if (_allow_degraded() or _evict_on_timeout()) \
                            and st.pending:
                        self._evict_ranks(missing, reason="round_timeout")
                        self._complete_round(st, key)
                        return
                    st.last_error = (gen, msg)
                    st.pending = []
                    st.contributors = set()
                    st.round += 1
                    st.round_done.notify_all()
                    raise _RoundError(msg)
                st.round_done.wait(timeout=min(remaining, 60.0))
            # round advanced while we waited: if it advanced BECAUSE a
            # peer's deadline fired, we share its fate
            if st.last_error is not None and st.last_error[0] == gen:
                raise _RoundError(st.last_error[1])

    def _do_barrier(self, rank=None):
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            self._barrier_ranks.add(rank)
            arrived = len({r for r in self._barrier_ranks
                           if r is not None}) \
                if rank is not None else self._barrier_count
            if arrived >= self._live_workers():
                self._barrier_count = 0
                self._barrier_ranks = set()
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
                return
            deadline = _time.monotonic() + _barrier_timeout()
            while self._barrier_gen == gen:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    missing = self._missing_ranks(self._barrier_ranks)
                    msg = ("barrier timed out after %gs "
                           "(MXNET_KVSTORE_BARRIER_TIMEOUT) waiting on "
                           "rank(s) %s" % (_barrier_timeout(), missing))
                    if _allow_degraded() or _evict_on_timeout():
                        self._evict_ranks(missing,
                                          reason="barrier_timeout")
                        self._barrier_count = 0
                        self._barrier_ranks = set()
                        self._barrier_gen += 1
                        self._barrier_cv.notify_all()
                        return
                    self._barrier_error = (gen, msg)
                    self._barrier_count = 0
                    self._barrier_ranks = set()
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                    raise _RoundError(msg)
                self._barrier_cv.wait(timeout=min(remaining, 60.0))
            if self._barrier_error is not None \
                    and self._barrier_error[0] == gen:
                raise _RoundError(self._barrier_error[1])

    def shutdown(self):
        """Hard-stop the server NOW: close the listener and every live
        connection (used by the SIGTERM handler in ``kvstore_server`` and
        the ``kill_server`` fault action — simulates preemption)."""
        self._stop.set()
        srv, self._srv_sock = self._srv_sock, None
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass
        conns, self._conns = list(self._conns), []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def run(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            # all interfaces: workers on OTHER hosts reach this server
            # via DMLC_PS_ROOT_URI (loopback-only would break true
            # multi-host)
            srv.bind(("", self._port))
            srv.listen(64)
            srv.settimeout(1.0)
        except BaseException:
            # a bind/listen failure (port taken) must not leak the FD —
            # shutdown() only closes the socket once _srv_sock is set
            srv.close()
            raise
        self._srv_sock = srv
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
                _tune_socket(conn)
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed by shutdown()
            self._conns.append(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        try:
            srv.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# worker-side store
# ---------------------------------------------------------------------------

class DistKVStore(KVStoreBase):
    """Worker-side distributed store (parity: KVStoreDist).

    Types: ``dist_sync`` / ``dist_device_sync`` (barrier-per-key sync,
    identical here — device vs cpu reduce location is moot on TPU) and
    ``dist_async`` (server applies pushes immediately).
    """

    def __init__(self, name="dist_sync"):
        self._type = name
        self._sync = "async" not in name
        self._rank = int(os.environ.get("DMLC_RANK",
                                        os.environ.get("DMLC_WORKER_ID", "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        self._root = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self._root_port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._socks = {}
        self._sock_res = {}   # server_id -> rescheck token (under _lock)
        # _lock guards the socket/conn-lock MAPS only (short holds).
        # Per-server _conn_locks serialize the wire exchange on one
        # connection (send+recv pair — replies are matched by ordering)
        # and the connect/retry path; a shard that is slow to accept or
        # mid-reconnect must not stall RPCs to every OTHER shard behind
        # a single client-wide lock (CD1103).
        self._lock = _lockcheck.named_lock("kv.cli.socks")
        self._conn_locks = {}
        self._gc = None
        self._optimizer = None
        # per-worker monotonic sequence number stamped on every mutating
        # RPC — the server dedups replays on it, making retries safe
        # (wire protocol v2, docs/fault_tolerance.md)
        self._seq = 0
        self._seq_lock = _lockcheck.named_lock("kv.cli.seq")
        # elastic membership (wire v3): last-known membership epoch PER
        # SERVER SHARD (each DistServer versions its own roster) plus an
        # optional training-step hint stamped into mutating meta so a
        # later JOIN can hand re-admitted workers the current step
        self._epochs = {}
        self._step_hint = None
        # keys this worker has init()ed — every worker runs the same init
        # sequence, so the local schema mirrors the cluster's and push/
        # pull key sets can be validated BEFORE any RPC (CC605)
        self._key_schema = set()

    def _next_seq(self):
        with self._seq_lock:
            self._seq += 1
            return self._seq

    # -- plumbing ----------------------------------------------------------
    def _shard(self, key):
        """Key → server id (parity: EncodeDefaultKey sharding).

        Deterministic across processes (Python's hash() is salted per
        process and would send the same key to different servers from
        different workers, deadlocking the sync barrier).
        """
        import zlib

        k = str(key)
        if k.isdigit():
            return int(k) % self._num_servers
        return zlib.crc32(k.encode()) % self._num_servers

    def _conn_lock(self, server_id):
        """Per-server connection lock (created on first use).  The map
        lock is held only for the lookup, never across I/O."""
        with self._lock:
            lk = self._conn_locks.get(server_id)
            if lk is None:
                lk = self._conn_locks[server_id] = \
                    _lockcheck.named_lock("kv.cli.conn")
            return lk

    def _sock(self, server_id):
        """Cached connection to one server shard.

        Caller holds that shard's ``_conn_lock`` — it serializes both
        the connect below and the send/recv exchange that follows, so
        the map lock covers only the dict lookups and the connect-retry
        sleeps stall nothing but RPCs to this (unreachable) shard.
        """
        with self._lock:
            s = self._socks.get(server_id)
        if s is not None:
            return s
        _inject("connect", server=server_id)
        addr = (self._root,
                _server_port(self._root_port, server_id))
        # retry refused connects: at job start the server process
        # may still be importing/binding (ps-lite retries the van
        # connect the same way).  The connect phase gets its OWN
        # short deadline — the wire-read timeout is sized for
        # sync-round reads waiting on slow compiles (30min); a dead
        # or misaddressed server must fail in seconds, not that
        deadline = _time.monotonic() + float(os.environ.get(
            "MXNET_KVSTORE_CONNECT_TIMEOUT",
            min(_wire_timeout() or 60, 60)))
        while True:
            try:
                s = socket.create_connection(addr, timeout=60)
                break
            except (ConnectionRefusedError, socket.timeout,
                    OSError):
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.2)
        try:
            _tune_socket(s)
            # every later read inherits the wire deadline: a wedged
            # server raises a diagnosable MXNetError instead of
            # blocking this worker forever
            s.settimeout(_wire_timeout())
            _client_handshake(s)
        except BaseException:
            # a mid-handshake failure (version skew, server dying while
            # we connect) must not leak the connected FD — only sockets
            # that reach _socks are ever evicted/closed by stop()
            s.close()
            raise
        with self._lock:
            self._socks[server_id] = s
            self._sock_res[server_id] = _rescheck.acquire(
                "socket", "server%d" % server_id,
                scope="kvclient:%x" % id(self))
        return s

    def _evict(self, server_id, sock=None):
        """Drop a (dead) cached socket so the next RPC reconnects.  A
        send/recv failure MUST evict: leaving the broken FD in ``_socks``
        would make every later RPC to that shard reuse it and fail."""
        with self._lock:
            cached = self._socks.get(server_id)
            if cached is not None and (sock is None or cached is sock):
                del self._socks[server_id]
                tok = self._sock_res.pop(server_id, None)
                try:
                    cached.close()
                except OSError:
                    pass
                _rescheck.release(tok)

    def _rpc_to(self, server_id, cmd, *fields, mutating=False):
        """One request/reply exchange with retry.

        Mutating commands get a fresh sequence number stamped into the
        v2 meta header ONCE, then the whole request is replayed verbatim
        on retry — the server's dedup cache makes the retry idempotent.
        Transport failures (reset, refused, EOF) evict the socket, back
        off exponentially with jitter, reconnect (re-handshaking), and
        replay.  Server-reported errors (CMD_ERR) and wire timeouts are
        NOT retried: the peer is alive and said no.

        While the profiler is recording, mutating meta also carries a
        span id ("rank:seq"); the server stamps the same id on its
        handler span, so ``telemetry.merge_traces`` correlates this
        worker-side RPC span with the server-side work it caused.

        Membership fencing (wire v3): a typed ``stale_epoch`` CMD_ERR is
        answered by adopting the epoch/roster the fence carries and
        re-sending the SAME request (same seq — the server's dedup cache
        keeps it exactly-once); a bounded resync budget, separate from
        the transport-retry budget, stops an epoch ping-pong.  A typed
        ``evicted`` CMD_ERR is terminal: this rank must ``join()``.
        """
        from .. import profiler as _prof

        _set_role("worker", rank=self._rank)
        cmd_name = _CMD_NAMES.get(cmd, str(cmd))
        span_id = None
        meta = None
        if mutating:
            meta = {"rank": self._rank, "seq": self._next_seq(),
                    "epoch": self._epochs.get(server_id, 0)}
            if self._step_hint is not None:
                meta["step"] = self._step_hint
            if _prof._recording():
                span_id = "%d:%d" % (self._rank, meta["seq"])
                meta["span"] = span_id
            fields = (meta,) + fields
        t_us0 = _prof._now_us()
        t_rpc0 = _time.perf_counter()
        attempts = _retries() + 1
        attempt = 0
        resyncs = 0
        last_err = None
        while attempt < attempts:
            s = None
            try:
                # per-SERVER serialization: the exchange (and any
                # reconnect inside _sock) holds only this shard's conn
                # lock, so a slow or dead shard can't head-of-line block
                # RPCs bound for the others
                with self._conn_lock(server_id):
                    s = self._sock(server_id)
                    _flight.record("kv.send", cmd=cmd_name,
                                   server=server_id, attempt=attempt,
                                   **({"span": span_id} if span_id
                                      else {}))
                    _send(s, cmd, *fields)
                    rcmd, rfields = _recv(s)
                _flight.record("kv.recv", cmd=cmd_name, server=server_id,
                               ok=rcmd == CMD_OK)
                if rcmd != CMD_OK:
                    err = rfields[0] if rfields else "<no detail>"
                    if meta is not None and isinstance(err, dict) \
                            and err.get("code") == "stale_epoch" \
                            and resyncs < 5:
                        # membership changed under us: adopt the new
                        # epoch and replay this request verbatim (NOT a
                        # transport retry — the server is alive and
                        # pointed us at the fresh roster)
                        resyncs += 1
                        new_epoch = int(err.get("epoch", 0))
                        self._epochs[server_id] = new_epoch
                        meta["epoch"] = new_epoch
                        _flight.record("membership.resync",
                                       rank=self._rank, server=server_id,
                                       epoch=new_epoch, cmd=cmd_name)
                        continue
                    if meta is not None and isinstance(err, dict) \
                            and err.get("code") == "evicted":
                        # terminal for this incarnation: a successor
                        # join()s as a fresh client — drop our cached
                        # connections instead of leaking them
                        self.close()
                        raise MXNetError(
                            "kvstore: rank %d was evicted from the "
                            "membership roster (server %d, epoch %s) — "
                            "re-admit with join() before mutating again"
                            % (self._rank, server_id, err.get("epoch")))
                    raise MXNetError(
                        "kvstore rpc (cmd %d, server %d) failed: %s"
                        % (cmd, server_id, err))
                if _metrics.enabled():
                    _metrics.histogram(
                        "mxnet_kvstore_rpc_seconds",
                        help="client RPC round-trip incl. retries",
                        command=cmd_name,
                    ).observe(_time.perf_counter() - t_rpc0)
                _prof.add_span("kv_" + cmd_name, t_us0, _prof._now_us(),
                               cat="kvstore",
                               args={"span": span_id} if span_id else None)
                return rfields
            except (ConnectionError, OSError) as e:
                last_err = e
                _flight.record("kv.retry", cmd=cmd_name, server=server_id,
                               attempt=attempt, error=type(e).__name__,
                               final=attempt + 1 >= attempts)
                if s is not None:
                    self._evict(server_id, s)
                attempt += 1
                if attempt >= attempts:
                    break
                _metrics.counter(
                    "mxnet_kvstore_rpc_retries_total",
                    help="transport-failure retries (backoff + replay)",
                    command=cmd_name).inc()
                _backoff_sleep(attempt - 1)
        _flight.crash_dump("kv_rpc_failed")
        raise MXNetError(
            "kvstore rpc (cmd %d, server %d) failed after %d attempt(s): "
            "%s (MXNET_KVSTORE_RETRIES/MXNET_KVSTORE_BACKOFF tune the "
            "retry schedule)" % (cmd, server_id, attempts, last_err))

    def _rpc(self, key, cmd, *fields, mutating=False):
        rfields = self._rpc_to(self._shard(key), cmd, *fields,
                               mutating=mutating)
        return rfields[0] if rfields else None

    # -- remote (server-side) profiling ------------------------------------
    def _profiler_broadcast(self, cfg):
        """Send one profiler command to EVERY server; returns replies in
        server-id order (parity: kSetProfilerParams,
        include/mxnet/kvstore.h:49)."""
        outs = []
        for sid in range(self._num_servers):
            rfields = self._rpc_to(sid, CMD_PROFILER, cfg)
            outs.append(rfields[0] if rfields else "")
        return outs

    def set_server_profiler_state(self, state):
        """Start/stop the profiler inside every server process."""
        self._profiler_broadcast({"action": "set_state", "state": state})

    def set_server_profiler_config(self, **config):
        self._profiler_broadcast({"action": "set_config",
                                  "config": config})

    def server_profiler_pause(self):
        """Pause event collection in every server process (routing parity
        with ``set_server_profiler_state`` — profiler.pause('server'))."""
        self._profiler_broadcast({"action": "pause"})

    def server_profiler_resume(self):
        self._profiler_broadcast({"action": "resume"})

    def server_profiler_dump(self, finished=True):
        """Every server writes its own chrome-trace file server-side."""
        self._profiler_broadcast({"action": "dump", "finished": finished})

    def server_profiler_dumps(self, reset=False):
        """Fetch each server's aggregate per-op stats table (one string
        per server)."""
        return self._profiler_broadcast({"action": "dumps",
                                         "reset": reset})

    # -- KVStore API -------------------------------------------------------
    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER,)

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    size = num_workers

    def set_gradient_compression(self, compression_params):
        if compression_params.get("type") != "2bit":
            raise MXNetError("only 2bit compression is supported")
        self._gc = GradientCompression(
            compression_params.get("threshold", 0.5))

    def _check_keys(self, op, keys):
        """CC605 pre-dispatch validation: duplicate keys in one call, or
        push/pull keys outside the init()ed schema, deadlock sync mode
        (the server barriers per key counting ONE contribution per worker
        per round) — fail here, before any bytes hit the wire."""
        ks = [str(k) for k in keys]
        dups = sorted({k for k in ks if ks.count(k) > 1})
        if dups:
            raise MXNetError(
                "CC605 (kvstore-key-divergence): duplicate key(s) %s in "
                "one %s call — sync mode counts one contribution per "
                "worker per key per round, so a double push wedges the "
                "round" % (dups, op))
        if op != "init" and self._key_schema:
            unknown = sorted(set(ks) - self._key_schema)
            if unknown:
                raise MXNetError(
                    "CC605 (kvstore-key-divergence): %s of key(s) %s not "
                    "in the initialized schema %s — workers must init() "
                    "every key on every worker first, or divergent key "
                    "sets deadlock the sync round"
                    % (op, unknown, sorted(self._key_schema)))

    def init(self, key, value):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        values = [value] if not isinstance(key, (list, tuple)) else value
        self._check_keys("init", keys)
        self._key_schema.update(str(k) for k in keys)
        for k, v in zip(keys, values):
            if self._rank == 0:
                # init ships host bytes over the wire  # mxlint: allow-host-sync
                arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
                self._rpc(k, CMD_INIT, str(k), arr, mutating=True)
        self.barrier()

    def _encode(self, key, v):
        """(kind, *wire_fields) for a pushed value."""
        if isinstance(v, _sp.RowSparseNDArray):
            return ("rsp", v.values.asnumpy(), v.indices.asnumpy(),
                    np.asarray(v.shape, np.int64))
        arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        if self._gc is not None:
            codes = self._gc.compress(str(key), arr)
            return ("2bit", codes, float(self._gc.threshold))
        return ("dense", arr)

    def _local_merge(self, value):
        vals = value if isinstance(value, (list, tuple)) else [value]
        if len(vals) == 1:
            return vals[0]
        if isinstance(vals[0], _sp.RowSparseNDArray):
            acc = vals[0]
            for v in vals[1:]:
                acc = acc + v
            return acc.compact()
        acc = vals[0].data()
        for v in vals[1:]:
            acc = acc + v.data()
        return NDArray(acc)

    def push(self, key, value, priority=0):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        values = [value] if not isinstance(key, (list, tuple)) else value
        self._check_keys("push", keys)
        for k, v in zip(keys, values):
            merged = self._local_merge(v)
            kind, *fields = self._encode(k, merged)
            self._rpc(k, CMD_PUSH, str(k), kind, *fields, mutating=True)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        outs = [out] if not isinstance(key, (list, tuple)) else out
        self._check_keys("pull", keys)
        for k, o in zip(keys, outs):
            val = self._rpc(k, CMD_PULL, str(k))
            dsts = o if isinstance(o, (list, tuple)) else [o]
            for dst in dsts:
                # copy=False: a dtype-matching pull (the common case)
                # must not clone 10s-of-MB gradients a second time
                dst._set_data(np.asarray(val).astype(dst.dtype,
                                                     copy=False))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if row_ids is None:
            return self.pull(key, out, priority)
        self._check_keys("row_sparse_pull", [key])
        rows_np = row_ids.asnumpy().astype(np.int64) \
            if hasattr(row_ids, "asnumpy") else np.asarray(row_ids,
                                                           np.int64)
        rows = self._rpc(key, CMD_ROW_SPARSE_PULL, str(key),
                         rows_np)
        dsts = out if isinstance(out, (list, tuple)) else [out]
        for dst in dsts:
            import jax.numpy as jnp

            full = jnp.zeros(dst.shape, dst.dtype).at[
                jnp.asarray(rows_np)].set(jnp.asarray(rows).astype(dst.dtype))
            dst._set_data(full)

    def barrier(self):
        # every worker must hit every server for a true global barrier;
        # mutating: a replayed barrier must not double-count this rank
        t0 = _time.perf_counter()
        for sid in range(self._num_servers):
            self._rpc_to(sid, CMD_BARRIER, mutating=True)
        if _metrics.enabled():
            # wall time this rank spent blocked = straggler skew seen
            # from here (the sum over all shards, like the wait itself)
            _metrics.histogram(
                "mxnet_kvstore_barrier_seconds",
                help="time this rank waited in a global barrier",
            ).observe(_time.perf_counter() - t0)

    # -- elastic membership (wire v3) --------------------------------------
    def set_step(self, step):
        """Stamp the current training step into later mutating meta; the
        server keeps the max, and JOIN hands it to re-admitted workers so
        they re-enter the loop at the right step boundary."""
        self._step_hint = int(step)

    def resync(self):
        """Refresh this worker's per-shard membership epochs (CMD_EPOCH).

        Normally unnecessary — the stale-epoch fence resyncs mutating
        RPCs automatically — but useful for observability and for a
        controller that wants the roster without mutating anything.
        Returns ``{server_id: {"epoch", "roster", "step"}}``.
        """
        infos = {}
        for sid in range(self._num_servers):
            rf = self._rpc_to(sid, CMD_EPOCH)
            info = rf[0] if rf else {}
            self._epochs[sid] = int(info.get("epoch", 0))
            infos[sid] = info
        return infos

    def join(self):
        """(Re-)admission into a running job (wire v3 scale-up).

        Sends JOIN to every server shard; each admits this rank at its
        next round boundary (MXNET_ELASTIC_JOIN_TIMEOUT), bumps its
        membership epoch, and returns the fresh epoch + roster + step.
        Returns ``{"step", "roster"}`` — the max step across shards, so
        the caller fast-forwards its loop before pulling resharded state
        through :meth:`pull`.
        """
        step, roster = 0, []
        for sid in range(self._num_servers):
            rf = self._rpc_to(sid, CMD_JOIN, {"rank": self._rank})
            info = rf[0] if rf else {}
            self._epochs[sid] = int(info.get("epoch", 0))
            step = max(step, int(info.get("step", 0)))
            roster = info.get("roster", roster)
        _flight.record("membership.join", rank=self._rank, step=step)
        return {"step": step, "roster": roster}

    def set_optimizer(self, optimizer):
        """Run the optimizer server-side (parity: SendCommandToServers)."""
        self._optimizer = optimizer
        if self._rank == 0:
            cfg = _optimizer_to_config(optimizer)
            for sid in range(self._num_servers):
                self._rpc_to(sid, CMD_SET_OPTIMIZER, cfg, mutating=True)
        self.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise MXNetError("server-side optimizer states live on the server")

    def load_optimizer_states(self, fname):
        raise MXNetError("server-side optimizer states live on the server")

    def close(self):
        """Drop every cached connection WITHOUT the ``stop()`` goodbye
        RPCs — teardown for an incarnation that is dead to the roster
        (evicted, or a harness-simulated kill): the server learns via
        timeout/eviction, never from us, and an abandoned incarnation
        must not sit on open FDs (MXNET_RESCHECK found exactly this)."""
        for sid in range(self._num_servers):
            self._evict(sid)

    def stop(self):
        # EVERY server shard gets this worker's stop (even ones this
        # worker never pushed to): the server quits once each distinct
        # rank has said goodbye.  Tolerate dead servers: stop() runs on
        # teardown paths where a shard may already have been killed.
        for sid in range(self._num_servers):
            try:
                self._rpc_to(sid, CMD_STOP, str(self._rank),
                             mutating=True)
            except (MXNetError, OSError):
                pass
            self._evict(sid)
        with self._lock:
            self._socks.clear()
            stale = list(self._sock_res.values())
            self._sock_res.clear()
        for tok in stale:
            _rescheck.release(tok)
