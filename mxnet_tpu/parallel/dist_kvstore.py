"""Distributed KVStore: multi-host parameter service over TCP (DCN path).

Capability parity with the reference's ps-lite stack:
``KVStoreDist`` (``src/kvstore/kvstore_dist.h:44``, worker side),
``KVStoreDistServer`` (``src/kvstore/kvstore_dist_server.h:155``, server
side: ``DataHandleEx:325``, sync aggregation ``ApplyUpdates:346`` that
waits for all workers per key, async immediate-apply mode, server-side
optimizer execution), key sharding across servers (``EncodeDefaultKey:263``),
row-sparse pulls (``:344-373``), and 2-bit gradient compression with
error-feedback residual (``gradient_compression.h:43-130``).

TPU-native stance: *intra-host* reduction rides ICI inside compiled
executables (``parallel.JitTrainStep`` psum) — this module is the
*inter-host* (DCN) tier, where the reference used ZMQ.  The wire is a
small length-prefixed-pickle protocol over TCP sockets; the scheduler
rendezvous of ps-lite collapses into the servers themselves (workers
connect straight to the server addresses derived from the root URI) —
one fewer process with identical observable semantics.

Environment (reference names, ``tools/launch.py`` sets them):
``DMLC_ROLE`` (worker|server|scheduler), ``DMLC_PS_ROOT_URI``,
``DMLC_PS_ROOT_PORT``, ``DMLC_NUM_WORKER``, ``DMLC_NUM_SERVER``.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading

import numpy as np

from ..base import MXNetError
from ..kvstore.base import KVStoreBase
from ..ndarray.ndarray import NDArray
from ..ndarray import sparse as _sp


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

def _send(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return pickle.loads(bytes(buf))


def _server_port(root_port, server_id):
    return int(root_port) + 1 + server_id


# ---------------------------------------------------------------------------
# gradient compression (2-bit with error feedback)
# ---------------------------------------------------------------------------

class GradientCompression:
    """2-bit quantization with residual (parity: gradient_compression.h).

    Values are mapped to {-threshold, 0, +threshold}; the quantization
    error accumulates in a per-key residual added to the next gradient
    (error feedback), so compression bias vanishes over steps.
    """

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self._residual = {}

    def compress(self, key, arr):
        t = self.threshold
        r = self._residual.get(key)
        g = arr + (r if r is not None else 0.0)
        codes = np.zeros(g.shape, np.int8)
        codes[g >= t] = 1
        codes[g <= -t] = -1
        self._residual[key] = g - codes.astype(g.dtype) * t
        return codes

    def decompress(self, codes, dtype=np.float32):
        return codes.astype(dtype) * self.threshold


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _KeyState:
    __slots__ = ("value", "pending", "round", "round_done", "lock")

    def __init__(self):
        self.value = None
        self.pending = []  # accumulated pushes this round
        self.round = 0
        self.round_done = threading.Condition()
        self.lock = threading.Lock()


class DistServer:
    """One parameter-server process (parity: KVStoreDistServer).

    Sync mode: pushes for a key buffer until every worker contributed,
    then the merged gradient is applied (optimizer if set, else
    overwrite-with-sum) and all blocked pushers are released — the
    reference's barrier-per-key (``ApplyUpdates:346-349``).
    Async mode: every push applies immediately.
    """

    def __init__(self, port, num_workers, sync=True):
        self._port = int(port)
        self._num_workers = int(num_workers)
        self._sync = sync
        self._keys = {}
        self._keys_lock = threading.Lock()
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        self._stop = threading.Event()

    def _key(self, k):
        with self._keys_lock:
            st = self._keys.get(k)
            if st is None:
                st = self._keys[k] = _KeyState()
            return st

    def _apply(self, st, key, merged):
        if self._updater is not None:
            idx = int(key) if str(key).isdigit() else key
            self._updater(idx, merged, st.value)
        else:
            if isinstance(merged, _sp.RowSparseNDArray):
                st.value._set_data(merged.scatter_add_into(
                    st.value.data() * 0))
            else:
                st.value._set_data(merged.data().astype(st.value.dtype))

    def _merge(self, pushes):
        first = pushes[0]
        if isinstance(first, _sp.RowSparseNDArray):
            acc = first
            for p in pushes[1:]:
                acc = acc + p
            return acc.compact()
        acc = pushes[0].data()
        for p in pushes[1:]:
            acc = acc + p.data()
        return NDArray(acc)

    def _handle(self, sock):
        try:
            while not self._stop.is_set():
                msg = _recv(sock)
                cmd = msg[0]
                if cmd == "INIT":
                    _, key, value = msg
                    st = self._key(key)
                    with st.lock:
                        if st.value is None:
                            st.value = NDArray(np.asarray(value))
                    _send(sock, ("OK",))
                elif cmd == "PUSH":
                    _, key, payload = msg
                    self._do_push(key, self._decode(payload))
                    _send(sock, ("OK",))
                elif cmd == "PULL":
                    _, key = msg
                    st = self._key(key)
                    with st.lock:
                        val = st.value.asnumpy()
                    _send(sock, ("OK", val))
                elif cmd == "ROW_SPARSE_PULL":
                    _, key, row_ids = msg
                    st = self._key(key)
                    with st.lock:
                        rows = st.value.asnumpy()[np.asarray(row_ids)]
                    _send(sock, ("OK", rows))
                elif cmd == "BARRIER":
                    self._do_barrier()
                    _send(sock, ("OK",))
                elif cmd == "SET_OPTIMIZER":
                    _, blob = msg
                    from .. import optimizer as opt_mod

                    self._optimizer = pickle.loads(blob)
                    self._updater = opt_mod.get_updater(self._optimizer)
                    _send(sock, ("OK",))
                elif cmd == "STOP":
                    _send(sock, ("OK",))
                    self._stop.set()
                else:
                    _send(sock, ("ERR", "unknown command %r" % (cmd,)))
        except (ConnectionError, OSError):
            pass

    @staticmethod
    def _decode(payload):
        kind = payload[0]
        if kind == "dense":
            return NDArray(payload[1])
        if kind == "rsp":
            _, vals, idx, shape = payload
            return _sp.RowSparseNDArray(np.asarray(vals),
                                        np.asarray(idx), shape)
        if kind == "2bit":
            _, codes, threshold = payload
            return NDArray(codes.astype(np.float32) * threshold)
        raise MXNetError("bad payload kind %r" % (kind,))

    def _do_push(self, key, value):
        st = self._key(key)
        if not self._sync:
            with st.lock:
                self._apply(st, key, value)
            return
        with st.round_done:
            st.pending.append(value)
            if len(st.pending) == self._num_workers:
                merged = self._merge(st.pending)
                with st.lock:
                    self._apply(st, key, merged)
                st.pending = []
                st.round += 1
                st.round_done.notify_all()
            else:
                gen = st.round
                while st.round == gen:
                    st.round_done.wait(timeout=60)

    def _do_barrier(self):
        with self._barrier_cv:
            gen = self._barrier_gen
            self._barrier_count += 1
            if self._barrier_count == self._num_workers:
                self._barrier_count = 0
                self._barrier_gen += 1
                self._barrier_cv.notify_all()
            else:
                while self._barrier_gen == gen:
                    self._barrier_cv.wait(timeout=60)

    def run(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # all interfaces: workers on OTHER hosts reach this server via
        # DMLC_PS_ROOT_URI (loopback-only would break true multi-host)
        srv.bind(("", self._port))
        srv.listen(64)
        srv.settimeout(1.0)
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        srv.close()


# ---------------------------------------------------------------------------
# worker-side store
# ---------------------------------------------------------------------------

class DistKVStore(KVStoreBase):
    """Worker-side distributed store (parity: KVStoreDist).

    Types: ``dist_sync`` / ``dist_device_sync`` (barrier-per-key sync,
    identical here — device vs cpu reduce location is moot on TPU) and
    ``dist_async`` (server applies pushes immediately).
    """

    def __init__(self, name="dist_sync"):
        self._type = name
        self._sync = "async" not in name
        self._rank = int(os.environ.get("DMLC_RANK",
                                        os.environ.get("DMLC_WORKER_ID", "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        self._root = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self._root_port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._socks = {}
        self._lock = threading.Lock()
        self._gc = None
        self._optimizer = None

    # -- plumbing ----------------------------------------------------------
    def _shard(self, key):
        """Key → server id (parity: EncodeDefaultKey sharding).

        Deterministic across processes (Python's hash() is salted per
        process and would send the same key to different servers from
        different workers, deadlocking the sync barrier).
        """
        import zlib

        k = str(key)
        if k.isdigit():
            return int(k) % self._num_servers
        return zlib.crc32(k.encode()) % self._num_servers

    def _sock(self, server_id):
        with self._lock:
            s = self._socks.get(server_id)
            if s is None:
                s = socket.create_connection(
                    (self._root, _server_port(self._root_port, server_id)),
                    timeout=60)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._socks[server_id] = s
            return s

    def _rpc(self, key, *msg):
        s = self._sock(self._shard(key))
        with self._lock:
            _send(s, msg)
            reply = _recv(s)
        if reply[0] != "OK":
            raise MXNetError("kvstore rpc failed: %r" % (reply,))
        return reply[1] if len(reply) > 1 else None

    # -- KVStore API -------------------------------------------------------
    @staticmethod
    def is_capable(capability):
        return capability in (KVStoreBase.OPTIMIZER,)

    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    size = num_workers

    def set_gradient_compression(self, compression_params):
        if compression_params.get("type") != "2bit":
            raise MXNetError("only 2bit compression is supported")
        self._gc = GradientCompression(
            compression_params.get("threshold", 0.5))

    def init(self, key, value):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        values = [value] if not isinstance(key, (list, tuple)) else value
        for k, v in zip(keys, values):
            if self._rank == 0:
                arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
                self._rpc(k, "INIT", str(k), arr)
        self.barrier()

    def _encode(self, key, v):
        if isinstance(v, _sp.RowSparseNDArray):
            return ("rsp", v.values.asnumpy(), v.indices.asnumpy(),
                    tuple(v.shape))
        arr = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
        if self._gc is not None:
            codes = self._gc.compress(str(key), arr)
            return ("2bit", codes, self._gc.threshold)
        return ("dense", arr)

    def _local_merge(self, value):
        vals = value if isinstance(value, (list, tuple)) else [value]
        if len(vals) == 1:
            return vals[0]
        if isinstance(vals[0], _sp.RowSparseNDArray):
            acc = vals[0]
            for v in vals[1:]:
                acc = acc + v
            return acc.compact()
        acc = vals[0].data()
        for v in vals[1:]:
            acc = acc + v.data()
        return NDArray(acc)

    def push(self, key, value, priority=0):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        values = [value] if not isinstance(key, (list, tuple)) else value
        for k, v in zip(keys, values):
            merged = self._local_merge(v)
            self._rpc(k, "PUSH", str(k), self._encode(k, merged))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = [key] if not isinstance(key, (list, tuple)) else key
        outs = [out] if not isinstance(key, (list, tuple)) else out
        for k, o in zip(keys, outs):
            val = self._rpc(k, "PULL", str(k))
            dsts = o if isinstance(o, (list, tuple)) else [o]
            for dst in dsts:
                dst._set_data(np.asarray(val).astype(dst.dtype))

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if row_ids is None:
            return self.pull(key, out, priority)
        rows_np = row_ids.asnumpy().astype(np.int64) \
            if hasattr(row_ids, "asnumpy") else np.asarray(row_ids,
                                                           np.int64)
        rows = self._rpc(key, "ROW_SPARSE_PULL", str(key), rows_np)
        dsts = out if isinstance(out, (list, tuple)) else [out]
        for dst in dsts:
            import jax.numpy as jnp

            full = jnp.zeros(dst.shape, dst.dtype).at[
                jnp.asarray(rows_np)].set(jnp.asarray(rows).astype(dst.dtype))
            dst._set_data(full)

    def barrier(self):
        # every worker must hit every server for a true global barrier
        for sid in range(self._num_servers):
            s = self._sock(sid)
            with self._lock:
                _send(s, ("BARRIER",))
                reply = _recv(s)
            if reply[0] != "OK":
                raise MXNetError("barrier failed")

    def set_optimizer(self, optimizer):
        """Run the optimizer server-side (parity: SendCommandToServers)."""
        self._optimizer = optimizer
        if self._rank == 0:
            blob = pickle.dumps(optimizer)
            for sid in range(self._num_servers):
                s = self._sock(sid)
                with self._lock:
                    _send(s, ("SET_OPTIMIZER", blob))
                    reply = _recv(s)
                if reply[0] != "OK":
                    raise MXNetError("set_optimizer failed")
        self.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise MXNetError("server-side optimizer states live on the server")

    def load_optimizer_states(self, fname):
        raise MXNetError("server-side optimizer states live on the server")

    def stop(self):
        for sid in list(self._socks):
            try:
                s = self._socks[sid]
                with self._lock:
                    _send(s, ("STOP",))
                    _recv(s)
                s.close()
            except OSError:
                pass
        self._socks.clear()
