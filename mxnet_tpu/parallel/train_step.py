"""JitTrainStep — the whole training step as ONE XLA executable.

This is the training-side completion of the ``CachedOp`` mapping
(SURVEY.md §3.3): where the reference runs forward (CachedOp), backward
(``CachedOp::Backward``, ``src/imperative/cached_op.cc:1254``) and the
optimizer (``optimizer_op.cc`` fused kernels, pushed per-parameter through
the engine) as hundreds of engine ops, here the gluon net's imperative
forward is traced once, ``jax.value_and_grad`` builds the backward, the
optimizer's pure ``_step`` updates every parameter, and XLA compiles the
lot into a single executable with donated parameter buffers (zero-copy
"mutation", the aliasing discipline from SURVEY §7 hard-part 1).

Distributed: given a ``Mesh``, parameters/optimizer state are placed with
``shard_params`` rules and the batch is sharded on its ``data`` axis; the
gradient all-reduce over ICI is inserted by XLA (GSPMD) *inside* the same
executable — the compiled equivalent of KVStore device mode.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd
from .. import random as _random
from .. import autograd as _autograd
from .. import optimizer as _opt_mod
from ..gluon import block as _block_mod


class JitTrainStep:
    """Compile net+loss+optimizer into one donated-buffer train step.

    Parameters
    ----------
    net : HybridBlock (initialized)
    loss : gluon loss Block, or None (net's first output IS the loss)
    optimizer : str or Optimizer
    optimizer_params : dict, for the str form
    mesh : a ``sharding.Mesh``, raw jax mesh, axes dict, or None.
        None picks up the ambient mesh when one is active (``with
        Mesh(...):`` / ``mx.tpu(mesh=...)``); otherwise single-device.
        Every spelling normalizes to the same jax mesh, so a step built
        from a mesh context compiles the identical executable (and
        produces bitwise-identical losses) as one built from the raw
        mesh — the substrate guarantee tests/test_sharding.py asserts.
    data_axis : mesh axis name carrying the batch dimension
    param_rule : fn(param_name, shape) -> PartitionSpec or None
        tensor-parallel sharding rule; None replicates parameters.
    rules : the declarative spelling of ``param_rule`` — ``"auto"``
        (the cost-model planner picks; see ``mxnet_tpu/planner/``),
        ``"dp"``/``"replicated"``, ``"megatron"``, or a callable
        (identical to ``param_rule``).  ``"auto"`` resolves at first
        step (when parameter shapes exist): the plan is kept on
        ``self.plan`` and ``MXNET_PLANNER_DRYRUN=1`` prints its
        ``explain()`` report to stderr.
    """

    def __init__(self, net, loss=None, optimizer='sgd',
                 optimizer_params=None, mesh=None, data_axis='data',
                 param_rule=None, donate=True, clip_global_norm=None,
                 rules=None):
        self._net = net
        self._loss = loss
        # global-norm grad clip fused into the step executable (the jitted
        # analogue of gluon.utils.clip_global_norm, reference
        # gluon/utils.py:118)
        self._clip_global_norm = clip_global_norm
        if isinstance(optimizer, str):
            optimizer = _opt_mod.create(optimizer,
                                        **(optimizer_params or {}))
        self._opt = optimizer
        from .. import sharding as _sharding

        if mesh is None:
            mesh = _sharding.current_mesh()
        self._mesh = _sharding.as_jax_mesh(mesh)
        self._data_axis = data_axis
        if rules is not None and param_rule is not None:
            raise MXNetError(
                "pass either rules= or param_rule=, not both (rules is "
                "the declarative spelling of the same knob)")
        self._rules = rules
        self.plan = None        # the planner's Plan under rules="auto"
        self._param_rule = param_rule
        self._params = None
        self._t = 0
        self._step_fn = None
        self._n_outputs = 1
        self._last_loss = None

    def _ensure_init(self, batch_nd):
        """Snapshot parameters; resolves deferred shapes with one forward."""
        if self._params is not None:
            return
        n_label = 1 if self._loss is not None else 0
        n_data = len(batch_nd) - n_label
        weights_ok = all(
            p._data is not None
            for p in self._net.collect_params().values())
        if not weights_ok:
            # a single throwaway forward resolves every deferred shape
            self._net(*batch_nd[:n_data])
        self._params = list(self._net.collect_params().values())
        for p in self._params:
            p._check_initialized()
        self._train_idx = [i for i, p in enumerate(self._params)
                           if p.grad_req != 'null']
        self._train_set = set(self._train_idx)
        # device copies of weights/state live here between steps; copied
        # (not aliased) because the step donates them — donating the very
        # buffers the gluon Parameters hold would invalidate p.data() after
        # step 1 on TPU (CPU ignores donation, which hid this in tests).
        # device_put COMMITS them to the accelerator: (a) jit outputs are
        # committed, so uncommitted initial weights would flip the cache
        # key after step 1 and recompile the whole executable; (b) NDArray
        # batches arrive committed to the DEFAULT context (cpu — reference
        # semantics), and a single cpu-committed argument would drag the
        # entire train step onto the host.
        from ..context import _best_context

        self._device = _best_context().jax_device
        dev = self._device
        self._weights = [jax.device_put(jnp.array(p.data().data()), dev)
                         for p in self._params]
        self._opt_state = [
            jax.tree_util.tree_map(
                lambda a: jax.device_put(a, dev),
                self._opt.create_state(i, self._weights[i]))
            if i in self._train_set else None
            for i in range(len(self._params))]
        if self._rules is not None:
            self._param_rule = self._resolve_rules(batch_nd)
        if self._mesh is not None:
            self._place_on_mesh(self._param_rule)
        self._tag_weights()

    def _tag_weights(self):
        """Attribute the live weight buffers to memdump (per-device param
        accounting — the 10% prediction-agreement contract in
        tests/test_planner.py).  Re-run after every step: donation frees
        the tagged buffers and the updated weights are NEW allocations."""
        from ..telemetry import memdump as _memdump

        if not _memdump.enabled():
            return
        for p, w in zip(self._params, self._weights):
            _memdump.tag(w, origin="param", label="train_step:%s" % p.name)

    # -- rules= resolution -------------------------------------------------
    def _optimizer_slots(self):
        """Per-weight optimizer state arrays (0 sgd, 1 momentum, 2 adam)
        — the planner prices optimizer residency with this."""
        st = self._opt.create_state(0, jnp.zeros((2,), jnp.float32))
        return len(jax.tree_util.tree_leaves(st))

    def _resolve_rules(self, batch_nd):
        rules = self._rules
        if callable(rules):
            return rules
        if self._mesh is None:
            raise MXNetError(
                "rules=%r needs a mesh (pass mesh= or enter a Mesh "
                "context)" % (rules,))
        if rules in ("dp", "replicated"):
            return None
        if rules == "megatron":
            from .tp_rules import megatron_rule

            return megatron_rule(mesh=self._mesh)
        if rules == "auto":
            import os
            import sys

            from .. import planner as _planner

            shape0 = tuple(batch_nd[0].shape)
            tokens = (shape0[0] * shape0[1] if len(shape0) >= 2
                      else (shape0[0] if shape0 else 1))
            self.plan = _planner.plan(
                self._params, self._mesh, data_axis=self._data_axis,
                step_tokens=tokens,
                optimizer_slots=self._optimizer_slots())
            if os.environ.get(_planner.ENV_DRYRUN, "") not in (
                    "", "0", "false", "False"):
                print(self.plan.explain(), file=sys.stderr)
            return self.plan.param_rule
        raise MXNetError(
            "unknown rules=%r (expected 'auto', 'dp'/'replicated', "
            "'megatron', or a param_rule callable)" % (rules,))

    # -- mesh placement ----------------------------------------------------
    @staticmethod
    def _np_host(arr):
        import numpy as _np

        return _np.asarray(arr)

    @property
    def _multiprocess(self):
        """Mesh spans devices of MORE than this process (multi-host run)."""
        return self._mesh is not None and jax.process_count() > 1

    @staticmethod
    def _put_global(arr, sharding):
        """Place a host-replicated array onto a (possibly multi-host)
        sharding.  ``device_put`` cannot target non-addressable devices;
        ``make_array_from_callback`` lets every process materialize just
        ITS shards from the identical host copy (works for replicated and
        sharded specs alike — the tp slice of a weight is host[idx])."""
        host = JitTrainStep._np_host(arr)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    def _place_on_mesh(self, param_rule):
        from .. import sharding as _sharding

        mesh = self._mesh
        def spec_for(p):
            s = param_rule(p.name, tuple(p.shape)) if param_rule else None
            return s if s is not None else P()
        self._param_shardings = [
            NamedSharding(mesh, spec_for(p)) for p in self._params]
        if _sharding.verify_enabled():
            for p, sh in zip(self._params, self._param_shardings):
                _sharding.verify_spec(mesh, sh.spec, shape=tuple(p.shape),
                                      what="param[%s]" % p.name)
        put = self._put_global if self._multiprocess else jax.device_put
        self._weights = [
            put(w, s)
            for w, s in zip(self._weights, self._param_shardings)]
        self._opt_state = [
            None if st is None else jax.tree_util.tree_map(
                lambda a: put(a, sh), st)
            for st, sh in zip(self._opt_state, self._param_shardings)]

    def _batch_sharding(self, arr):
        return NamedSharding(
            self._mesh, P(self._data_axis, *([None] * (arr.ndim - 1))))

    def _place_batch(self, batch_nd):
        """device_put batch arrays: data-axis sharded on a mesh, else the
        single training device.

        Multi-host: each process passes its HOST-LOCAL rows; the global
        batch is their concatenation along the data axis (the reference's
        per-worker data shard semantics), assembled without cross-host
        transfers."""
        if self._multiprocess:
            from jax.experimental import multihost_utils

            return [multihost_utils.host_local_array_to_global_array(
                        self._np_host(b.data()), self._mesh,
                        P(self._data_axis,
                          *([None] * (b.data().ndim - 1))))
                    for b in batch_nd]
        if self._mesh is not None:
            return [jax.device_put(b.data(), self._batch_sharding(b.data()))
                    for b in batch_nd]
        return [jax.device_put(b.data(), self._device) for b in batch_nd]

    def _out_shardings(self):
        """(weights, opt_state, loss) shardings for any step executable."""
        return (
            self._param_shardings,
            [None if st is None else jax.tree_util.tree_map(
                lambda _, s=sh: s, st)
             for st, sh in zip(self._opt_state, self._param_shardings)],
            NamedSharding(self._mesh, P()))

    # -- the pure step ----------------------------------------------------
    def _build(self, batch_arrays):
        net, loss_block = self._net, self._loss
        params = self._params
        train_idx = list(self._train_idx)
        opt = self._opt
        n_label = 1 if loss_block is not None else 0
        n_data = len(batch_arrays) - n_label
        meta = {}

        def forward_loss(train_ws, all_ws, batch):
            st = _block_mod._trace_st()
            prev = (st.param_map, st.aux_updates, st.active)
            ws = list(all_ws)
            for i, w in zip(train_idx, train_ws):
                ws[i] = w
            st.param_map = {
                id(p): NDArray(w) for p, w in zip(params, ws)}
            st.aux_updates = []
            st.active = True
            try:
                data_nd = [NDArray(b) for b in batch[:n_data]]
                # train mode (not recording): BN/dropout use batch stats;
                # the grad comes from jax.value_and_grad, not the tape
                with _autograd.train_mode():
                    out = net._forward_imperative(*data_nd)
                    outs = [out] if isinstance(out, NDArray) else list(out)
                    if loss_block is not None:
                        label_nd = [NDArray(b) for b in batch[n_data:]]
                        loss = loss_block(outs[0], *label_nd)
                    else:
                        loss = outs[0]
                loss_val = jnp.mean(loss.data())
                idx_of = {id(p): i for i, p in enumerate(params)}
                aux = [(idx_of[id(p)], v) for p, v in st.aux_updates]
                meta['n_outputs'] = len(outs)
                return loss_val, aux
            finally:
                st.param_map, st.aux_updates, st.active = prev

        clip_norm = self._clip_global_norm

        def step(key, lr, weights, opt_state, t, *batch):
            with _random.trace_key_scope(key):
                train_ws = [weights[i] for i in train_idx]
                (loss_val, aux), grads = jax.value_and_grad(
                    forward_loss, has_aux=True)(train_ws, weights, batch)
            if clip_norm is not None:
                from ..gluon.utils import global_norm_scale

                grads, _ = global_norm_scale(grads, clip_norm)
            new_weights = list(weights)
            new_state = list(opt_state)
            for j, i in enumerate(train_idx):
                g = grads[j]
                w, st_i = weights[i], opt_state[i]
                wd = opt._get_wd(i)
                lr_i = lr * opt.lr_mult.get(
                    params[i].name, opt.lr_mult.get(i, 1.0))
                # _step applies clip/rescale itself (see Optimizer._step
                # implementations)
                nw, ns = opt._step(w, g, st_i, lr_i, wd, t)
                # pin dtypes: f32 lr/wd scalars promote bf16 updates to
                # f32, which would change the carried weight dtype and
                # force a retrace (+ mixed-dtype convs) on the next step
                new_weights[i] = nw.astype(w.dtype)
                new_state[i] = jax.tree_util.tree_map(
                    lambda a, b: a.astype(b.dtype), ns, st_i)
            for i, v in aux:
                new_weights[i] = v.astype(weights[i].dtype)
            return new_weights, new_state, loss_val

        jit_kwargs = {}
        if self._mesh is not None:
            jit_kwargs['out_shardings'] = self._out_shardings()
        self._raw_step = step
        return jax.jit(step,
                       donate_argnums=(2, 3),
                       **jit_kwargs)

    # -- public API --------------------------------------------------------
    def _scalar_args(self, key, lr, t):
        """key/lr/t for the step executable.

        Multi-host: every argument of a global jit must be a GLOBAL array
        — and the RNG key must be the SAME on every process (identical
        dropout masks keep the replicas in lockstep, the property the
        reference gets from broadcasting seeds through the kvstore).
        Rank 0's key is broadcast ONCE; per-step keys derive from it
        deterministically (``fold_in(t)``) so the steady-state step pays
        no cross-host collective.
        """
        if not self._multiprocess:
            return key, lr, t
        from jax.experimental import multihost_utils

        if not hasattr(self, "_mh_rep"):
            self._mh_rep = NamedSharding(self._mesh, P())
            self._mh_base_key = multihost_utils.broadcast_one_to_all(key)
        key = jax.random.fold_in(self._mh_base_key, int(t))
        return (self._put_global(key, self._mh_rep),
                self._put_global(lr, self._mh_rep),
                self._put_global(t, self._mh_rep))

    def step(self, *batch):
        """Run one train step; returns the (device, async) scalar loss."""
        batch_nd = [b if isinstance(b, NDArray) else nd.array(b)
                    for b in batch]
        self._ensure_init(batch_nd)
        arrays = self._place_batch(batch_nd)
        self._batch_avals = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays)
        if self._step_fn is None:
            self._step_fn = self._build(arrays)
        self._t += 1
        self._opt.num_update = self._t
        key, lr, t = self._scalar_args(
            _random.next_key(),
            jnp.asarray(self._opt.learning_rate, jnp.float32),
            jnp.asarray(self._t, jnp.int32))
        self._weights, self._opt_state, loss = self._step_fn(
            key, lr, self._weights, self._opt_state, t, *arrays)
        self._tag_weights()
        self._last_loss = loss
        return loss

    def step_n(self, n, *batch):
        """Run ``n`` train steps as ONE device-side loop (single dispatch).

        The whole loop — n × (forward, backward, optimizer) — compiles
        into one executable via ``lax.fori_loop`` with the weights and
        optimizer state as the carry, so host↔device latency is paid
        once per n steps instead of per step.  Per-iteration RNG keys
        are folded from one base key.  Returns the last step's loss.

        Mesh mode: the loop jit pins ``out_shardings`` to the parameter/
        state shardings (same as ``step()``), so the carried weights keep
        their tp/dp placement across iterations and the n-step
        single-dispatch methodology works on a pod the same as on one
        chip.
        """
        from jax import lax

        sched = getattr(self._opt, "lr_scheduler", None)
        sched_traced = None
        if sched is not None:
            try:
                sched_traced = sched.traced(jnp.asarray(1, jnp.int32))
            except Exception:
                sched_traced = None
            sched_traced = sched.traced if sched_traced is not None else None
        if sched is not None and sched_traced is None:
            # custom scheduler without a pure jnp form: fall back to
            # per-step dispatch so every update sees its scheduled lr
            import warnings

            warnings.warn(
                "step_n: lr_scheduler has no traced() pure form -> "
                "falling back to per-step dispatch; subclass "
                "LRScheduler.traced to keep the device-side loop",
                stacklevel=2)
            loss = None
            for _ in range(int(n)):
                loss = self.step(*batch)
            return loss
        batch_nd = [b if isinstance(b, NDArray) else nd.array(b)
                    for b in batch]
        self._ensure_init(batch_nd)
        arrays = self._place_batch(batch_nd)
        if self._step_fn is None:
            self._step_fn = self._build(arrays)
        if not hasattr(self, "_raw_step"):
            # _step_fn came from load_executable: the loop body needs the
            # traceable python step, so build it once (no call, no compile)
            self._build(arrays)
        if not hasattr(self, "_step_n_cache"):
            self._step_n_cache = {}
        # keyed on the scheduler OBJECT too: swapping in a different
        # scheduler must not reuse a loop that closed over the old one
        # (mutating a scheduler's fields in place after the first step_n
        # still won't retrace — schedules are constants of the executable)
        sched_key = (n, id(sched) if sched_traced is not None else None)
        fn = self._step_n_cache.get(sched_key)
        if fn is None:
            raw = self._raw_step

            def loop(key, lr, weights, state, t, *arrs):
                def body(i, carry):
                    w, s, _ = carry
                    # t is the count BEFORE this window; iteration i runs
                    # update number t+i+1 (step() uses 1-based counts —
                    # Adam's bias correction divides by 1-beta^t, so a
                    # 0-based counter would produce 0/0 on step one)
                    # scheduled lr is evaluated device-side per iteration
                    lr_i = (sched_traced(t + i + 1).astype(jnp.float32)
                            if sched_traced is not None else lr)
                    nw, ns, loss = raw(jax.random.fold_in(key, i), lr_i,
                                       w, s, t + i + 1, *arrs)
                    return (nw, ns, loss.astype(jnp.float32))

                return lax.fori_loop(
                    0, n, body,
                    (weights, state, jnp.float32(0.0)))

            jit_kwargs = {}
            if self._mesh is not None:
                jit_kwargs["out_shardings"] = self._out_shardings()
            fn = jax.jit(loop, donate_argnums=(2, 3), **jit_kwargs)
            self._step_n_cache[sched_key] = fn
        self._opt.num_update = self._t + n
        key, lr, t = self._scalar_args(
            _random.next_key(),
            jnp.asarray(self._opt.learning_rate, jnp.float32),
            jnp.asarray(self._t, jnp.int32))
        self._weights, self._opt_state, loss = fn(
            key, lr, self._weights, self._opt_state, t, *arrays)
        self._tag_weights()
        self._t += n
        self._last_loss = loss
        return loss

    def _checkpoint_entries(self):
        """Yield ``(name, global host array, spec)`` for every weight and
        optimizer-state leaf — each array ONCE in its logical shape, so
        the file restores onto any mesh (sharding/checkpoint.py)."""
        def fetch(a):
            if self._multiprocess and not a.is_fully_addressable:
                from jax.experimental import multihost_utils

                a = multihost_utils.process_allgather(a, tiled=True)
            return jax.device_get(a)

        specs = [sh.spec for sh in self._param_shardings] \
            if self._mesh is not None else [None] * len(self._params)
        # entry keys are POSITIONAL (weights/<i>, opt/<i>/<leaf>), not
        # name-keyed: gluon's auto-naming counter gives the same layer a
        # different name in every process ("dense0" vs "dense2"), while
        # parameter ORDER is a function of the net's structure alone;
        # the human-readable names ride in the index meta instead
        for i, (w, spec) in enumerate(zip(self._weights, specs)):
            yield "weights/%d" % i, fetch(w), spec
        for i, (st, spec) in enumerate(zip(self._opt_state, specs)):
            if st is None:
                continue
            for j, leaf in enumerate(jax.tree_util.tree_leaves(st)):
                yield "opt/%d/%d" % (i, j), fetch(leaf), spec

    def save_states(self, fname):
        """Checkpoint weights + optimizer state + update count
        (resume-able mid-training; Trainer.save_states analogue for the
        compiled path) in the mesh-shape-agnostic MXGC1 format: each
        array stored once, globally, with its PartitionSpec and a
        per-entry checksum — restore onto ANY mesh whose axes divide the
        spec.  Multi-host: call on every process (each writes identical
        global state; rank-suffix the fname if the filesystem is
        shared)."""
        from .. import sharding as _shd

        if self._params is None:
            raise MXNetError("save_states before the first step")
        meta = {"kind": "jit_train_step", "t": int(self._t),
                "param_names": [p.name for p in self._params],
                "opt_leaves": [0 if st is None else len(
                    jax.tree_util.tree_leaves(st))
                    for st in self._opt_state]}
        if self._mesh is not None:
            meta["mesh_axes"] = {str(k): int(self._mesh.shape[k])
                                 for k in self._mesh.axis_names}
        _shd.save_global(fname, self._checkpoint_entries(), meta=meta)

    def load_states(self, fname):
        """Restore a save_states checkpoint (same net/optimizer config)
        onto the CURRENT placement — the checkpoint's mesh shape is
        irrelevant (a dp=8 file restores at dp=4/dp=6/single-device:
        global arrays are re-placed through this step's shardings).

        Requires placement to exist — run ONE step (any batch) first so
        shapes/shardings are established, then load; the loaded state
        fully overwrites that step's effects.  Legacy pickled
        checkpoints still load (sniffed by magic); corruption in either
        format surfaces as MXNetError, never a raw unpickling error."""
        from .. import sharding as _shd

        if self._params is None:
            raise MXNetError(
                "load_states needs initialized placement: run one step, "
                "or call after net.initialize + a step")
        if _shd.is_global_checkpoint(fname):
            entries, meta = _shd.load_global(fname)
            weights, opt_state = self._states_from_entries(fname, entries)
            t = int(meta.get("t", 0))
        else:
            weights, opt_state, t = self._load_legacy_states(fname)
        if self._mesh is not None:
            put = (self._put_global if self._multiprocess
                   else jax.device_put)
            self._weights = [put(w, s) for w, s in
                             zip(weights, self._param_shardings)]
            self._opt_state = [
                None if st is None else jax.tree_util.tree_map(
                    lambda a, sh=sh: put(a, sh), st)
                for st, sh in zip(opt_state, self._param_shardings)]
        else:
            dev = self._device
            self._weights = [jax.device_put(w, dev) for w in weights]
            self._opt_state = [
                None if st is None else jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, dev), st)
                for st in opt_state]
        self._t = t
        self._opt.num_update = self._t

    def _states_from_entries(self, fname, entries):
        """Rebuild (weights list, opt_state trees) from MXGC1 entries,
        validating logical shapes against the live placement."""
        weights = []
        for i, p in enumerate(self._params):
            name = "weights/%d" % i
            ent = entries.get(name)
            if ent is None:
                raise MXNetError(
                    "checkpoint %s: missing entry %r (param %s) — the "
                    "file was written by a different net"
                    % (fname, name, p.name))
            if tuple(ent["array"].shape) != tuple(p.shape):
                raise MXNetError(
                    "checkpoint %s: entry %r has logical shape %s, the "
                    "live parameter %s wants %s"
                    % (fname, name, ent["array"].shape, p.name,
                       tuple(p.shape)))
            weights.append(ent["array"])
        opt_state = []
        for i, st in enumerate(self._opt_state):
            if st is None:
                opt_state.append(None)
                continue
            treedef = jax.tree_util.tree_structure(st)
            leaves = []
            for j in range(treedef.num_leaves):
                name = "opt/%d/%d" % (i, j)
                ent = entries.get(name)
                if ent is None:
                    raise MXNetError(
                        "checkpoint %s: missing optimizer entry %r "
                        "(optimizer config changed?)" % (fname, name))
                leaves.append(ent["array"])
            opt_state.append(jax.tree_util.tree_unflatten(treedef,
                                                          leaves))
        return weights, opt_state

    @staticmethod
    def _load_legacy_states(fname):
        """Pre-MXGC1 pickled payload; unpickling failures surface as
        MXNetError (a torn legacy file must not raise a raw
        UnpicklingError)."""
        import pickle

        try:
            with open(fname, "rb") as f:
                payload = pickle.load(f)
            return (payload["weights"], payload["opt_state"],
                    int(payload["t"]))
        except MXNetError:
            raise
        except Exception as e:  # noqa: BLE001 — any torn-pickle shape
            raise MXNetError(
                "checkpoint %s is neither MXGC1 nor a loadable legacy "
                "pickle (%s: %s) — the file is corrupt or truncated"
                % (fname, type(e).__name__, e))

    def save_executable(self, fname):
        """AOT-export the compiled train step (compile_cache.py bundle).

        A fleet restart then calls ``load_executable`` and compiles
        NOTHING — the multi-minute cold trace+compile of the full
        step collapses to a deserialization.  Run at least one ``step``
        first (the executable and its placement must exist).  Pairs with
        ``save_states`` — this file carries the *program*, the states
        checkpoint carries the *data*.
        """
        from .. import compile_cache as _ccache

        if self._step_fn is None:
            raise MXNetError(
                "save_executable before the first step: run one step so "
                "the executable exists")
        if self._mesh is not None:
            raise MXNetError(
                "save_executable does not support mesh-placed steps: "
                "sharded executables are not portable across process "
                "topologies")

        def aval(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        w_avals = [aval(w) for w in self._weights]
        s_avals = [None if s is None else jax.tree_util.tree_map(aval, s)
                   for s in self._opt_state]
        compiled = self._step_fn.lower(
            aval(_random.next_key()),
            jax.ShapeDtypeStruct((), jnp.float32),
            w_avals, s_avals,
            jax.ShapeDtypeStruct((), jnp.int32),
            *self._batch_avals).compile()
        entry = {
            "blob": _ccache.serialize_compiled(compiled),
            "param_names": [p.name for p in self._params],
            "weight_sig": [(tuple(w.shape), str(w.dtype))
                           for w in self._weights],
            "batch_sig": [(tuple(a.shape), str(a.dtype))
                          for a in self._batch_avals],
        }
        _ccache.save_bundle(fname, {"step": entry},
                            meta={"kind": "train_step"})

    def load_executable(self, fname, *batch):
        """Load a ``save_executable`` bundle instead of trace+compiling.

        ``batch`` is one example batch (same shapes/dtypes as training
        will use) — it establishes parameter placement exactly like the
        first ``step`` would, and is NOT stepped on.  Raises MXNetError
        when the bundle's parameter set or batch signature does not match
        this net — at load, not on the first training step.
        """
        from .. import compile_cache as _ccache

        if self._mesh is not None:
            raise MXNetError(
                "load_executable does not support mesh-placed steps")
        batch_nd = [b if isinstance(b, NDArray) else nd.array(b)
                    for b in batch]
        self._ensure_init(batch_nd)
        arrays = self._place_batch(batch_nd)
        doc = _ccache.load_bundle(fname)
        entry = doc["entries"].get("step")
        if entry is None or doc.get("meta", {}).get("kind") != "train_step":
            raise MXNetError(
                "%s is not a JitTrainStep executable bundle" % fname)
        names = [p.name for p in self._params]
        if entry["param_names"] != names:
            raise MXNetError(
                "load_executable: bundle was exported with parameters %s "
                "but this net has %s" % (entry["param_names"], names))
        w_sig = [(tuple(w.shape), str(w.dtype)) for w in self._weights]
        if entry["weight_sig"] != w_sig:
            raise MXNetError(
                "load_executable: weight signature mismatch — bundle %s "
                "vs net %s" % (entry["weight_sig"], w_sig))
        b_sig = [(tuple(a.shape), str(a.dtype)) for a in arrays]
        if entry["batch_sig"] != b_sig:
            raise MXNetError(
                "load_executable: executable was compiled for batch %s "
                "but got %s" % (entry["batch_sig"], b_sig))
        self._step_fn = _ccache.deserialize_compiled(entry["blob"])
        self._batch_avals = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays)
        return self

    def sync_params(self):
        """Write the jitted weights back into the gluon Parameters.

        Multi-host: a parameter sharded ACROSS processes spans
        non-addressable devices and cannot be fetched directly —
        all-gather it first (every process ends with the full value,
        reference broadcast-from-kvstore semantics)."""
        for p, w in zip(self._params, self._weights):
            if self._multiprocess and not w.is_fully_addressable:
                from jax.experimental import multihost_utils

                w = multihost_utils.process_allgather(w, tiled=True)
            p.set_data(w)

    @property
    def loss(self):
        return None if self._last_loss is None else float(self._last_loss)
