"""General utilities (parity: python/mxnet/util.py).

The NumPy-semantics switches are straight re-exports of
``numpy_extension`` (the single source of truth for the thread-local
np-shape/np-array flags); device helpers answer for the TPU world.
"""
from __future__ import annotations

import functools
import os

from .numpy_extension import (  # noqa: F401
    set_np, reset_np, set_np_shape, is_np_shape, is_np_array,
    np_shape, np_array, use_np,
)


def makedirs(d):
    """Create directories recursively if they don't exist
    (parity: util.py:42)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count():
    """Number of GPU devices (parity: util.py:52) — delegates to the
    same platform probe as ``mx.num_gpus`` so the two never disagree.
    TPU chips are counted by ``get_accelerator_count``."""
    from .context import num_gpus

    return num_gpus()


def get_accelerator_count():
    """Number of accelerator (TPU/GPU) devices — the TPU-world analogue
    of the reference's GPU probes."""
    try:
        import jax

        return sum(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return 0


def use_np_shape(func):
    """Decorator applying np-shape semantics (parity: util.py:254)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)
    return wrapper


def use_np_array(func):
    """Decorator applying np-array semantics (parity: util.py:430)."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_array(True):
            return func(*args, **kwargs)
    return wrapper


def set_module(module):
    """Decorator overriding ``__module__`` for cleaner docs
    (parity: util.py:335)."""
    def deco(fn):
        if module is not None:
            fn.__module__ = module
        return fn
    return deco


def wraps_safely(wrapped, assigned=functools.WRAPPER_ASSIGNMENTS):
    """functools.wraps tolerating missing attributes
    (parity: util.py:243)."""
    return functools.wraps(
        wrapped, assigned=(a for a in assigned if hasattr(wrapped, a)))
