"""``mx.operator`` — user-defined operators in Python.

Capability parity with the reference CustomOp stack
(``python/mxnet/operator.py``: ``CustomOp``, ``CustomOpProp``,
``register``; C++ side ``src/operator/custom/custom-inl.h:52`` runs the
Python callbacks on a dedicated worker thread).

TPU-native mechanism: no callback thread — the imperative path simply
runs ``forward``/``backward`` eagerly on NDArrays and records one tape
node whose vjp re-enters ``backward``; under a ``hybridize()`` trace the
same Python code executes over tracer-backed NDArrays, so *traceable*
custom ops fuse into the XLA executable (the reference could never fuse
a CustomOp — a genuine upgrade), while non-traceable ones (asnumpy etc.)
keep working imperatively exactly like the reference.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from . import autograd

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base class for user ops (parity: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad request."""
        if req in ("null", 0):
            return
        if req in ("add", 3):
            dst._set_data(dst.data() + src.data())
        else:  # write / inplace
            dst._set_data(src.data().astype(dst.dtype))


class CustomOpProp:
    """Op metadata + factory (parity: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type`` (parity:
    operator.py:legacy register)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return deco


def get_prop_cls(op_type):
    cls = _CUSTOM_REGISTRY.get(op_type)
    if cls is None:
        raise MXNetError("custom op %r is not registered" % (op_type,))
    return cls


def custom(*inputs, op_type=None, **kwargs):
    """Run a registered custom op (parity: mx.nd.Custom)."""
    from .ndarray.ndarray import NDArray
    from . import ndarray as nd
    from .context import current_context

    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    prop = get_prop_cls(op_type)(**{k: str(v) for k, v in kwargs.items()})
    in_shapes = [tuple(x.shape) for x in inputs]
    ishapes, oshapes, aux_shapes = prop.infer_shape(list(in_shapes))
    in_types = [x.dtype for x in inputs]
    _, otypes, _ = prop.infer_type(list(in_types))
    ctx = inputs[0].context if inputs else current_context()
    op = prop.create_operator(ctx, ishapes, in_types)

    out_data = [nd.empty(tuple(s), dtype=t, ctx=ctx)
                for s, t in zip(oshapes, otypes)]
    in_list = list(inputs)
    is_train = autograd.is_training() or autograd.is_recording()
    with autograd.pause():
        op.forward(is_train=is_train, req=["write"] * len(out_data),
                   in_data=in_list, out_data=out_data, aux=[])

    recording = autograd.is_recording() and any(
        x._in_graph for x in in_list)
    if recording:
        def vjp_fn(cts):
            in_grad = [nd.zeros(x.shape, dtype=x.dtype, ctx=ctx)
                       for x in in_list]
            with autograd.pause():
                op.backward(req=["write"] * len(in_grad),
                            out_grad=[NDArray(c) for c in cts],
                            in_data=in_list, out_data=out_data,
                            in_grad=in_grad, aux=[])
            return tuple(g.data() for g in in_grad)

        node = autograd.TapeNode(
            vjp_fn, in_list,
            [(o.shape, o.dtype) for o in out_data],
            op_name="Custom:" + op_type)
        for i, o in enumerate(out_data):
            o._tape_node = node
            o._tape_index = i
    return out_data[0] if len(out_data) == 1 else out_data


# surfaced as mx.nd.Custom / mx.sym-compatible callable
Custom = custom
