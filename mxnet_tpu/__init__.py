"""mxnet_tpu — a TPU-native deep learning framework with MXNet's capabilities.

A from-scratch rebuild of Apache MXNet's capability surface (reference:
kalakuer/incubator-mxnet) designed for TPU hardware: NDArrays are PJRT
buffers, operators are XLA computations (Pallas for the hot fused kernels),
``hybridize()`` lowers a captured graph to a single XLA executable, and the
KVStore runs on XLA collectives over ICI/DCN instead of NCCL/ps-lite.

Usage mirrors MXNet::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"


def _platform_override_needed(env_val, cfg_val):
    """Should a ``JAX_PLATFORMS`` env value replace the config value?

    Refuse ONLY the strip direction: when the env list is a (strict or
    equal) prefix of the config list, the config is the same intent plus
    extra fallback platforms a deployment plugin added — e.g. config
    ``"axon,cpu"`` (accelerator + host-CPU staging platform) under env
    ``"axon"``.  Clobbering that to the bare env value silently pushes
    host-side buffers onto the chip (observed: ResNet-50 batch-256 OOM
    on a 16G v5e with ``"axon"`` forced over ``"axon,cpu"``).  Every
    other disagreement — different primary (the tunnel-outage case this
    guard exists for: ``JAX_PLATFORMS=cpu`` subprocesses), or an env
    that ADDS platforms over a bare config — is an explicit request and
    must win.  Pure function; the probe snippets in bench.py and
    __graft_entry__.py inline the same rule (keep them in sync).
    """
    env_list = [p.strip() for p in env_val.split(",") if p.strip()]
    cfg_list = [p.strip() for p in cfg_val.split(",") if p.strip()]
    return env_list != cfg_list[:len(env_list)]


def _honor_platform_env():
    """Make a ``JAX_PLATFORMS`` environment override actually win.

    The deployment image may register an accelerator PJRT plugin at
    interpreter startup and set the platform through jax's *config* API;
    config beats the env var, so a subprocess launched with
    ``JAX_PLATFORMS=cpu`` would still try to initialize the accelerator
    backend — and hang, not raise, if the device link is down.  Pushing
    the env value back through the config API (before any backend is
    instantiated) restores the documented env-var contract for every
    process that imports this package (tools/launch.py servers and
    workers, tools/diagnose.py, test subprocesses).
    """
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            return  # too late to redirect a live backend; leave it be
        current = str(getattr(jax.config, "jax_platforms", "") or "")
        if not _platform_override_needed(plat, current):
            return
        jax.config.update("jax_platforms", plat)
    except Exception:
        pass  # never let platform plumbing break the import


_honor_platform_env()


def _honor_int64_tensor_size():
    """``MXNET_INT64_TENSOR_SIZE=1`` enables 64-bit index VALUES
    (parity: the reference's ``USE_INT64_TENSOR_SIZE`` compile flag,
    tested by tests/nightly/test_large_array.py — here a runtime flag).

    Array *shapes* are 64-bit regardless (XLA native); this flag lifts
    jax's default int32 truncation so index arithmetic and integer
    reductions past 2^31 are exact too.  Opt-in because it also widens
    numpy-style default promotions, exactly like the reference flag
    changes framework-wide index types.  See docs/large_tensor.md.
    """
    import os

    if os.environ.get("MXNET_INT64_TENSOR_SIZE", "0") not in ("1", "true"):
        return
    try:
        import jax

        jax.config.update("jax_enable_x64", True)
    except Exception as e:
        # an explicit opt-in to exact >2^31 index math must never fail
        # silently — truncation would corrupt numerics downstream
        import warnings

        warnings.warn(
            "MXNET_INT64_TENSOR_SIZE=1 requested but enabling jax x64 "
            "failed (%s): index values past 2^31 will truncate" % (e,))


_honor_int64_tensor_size()


def _honor_compile_cache():
    """Persistent XLA executable cache, ON by default (accelerator procs).

    ``MXNET_COMPILE_CACHE=0`` disables, ``=1`` forces on, a *path* value
    forces on with that directory; ``MXNET_COMPILE_CACHE_DIR`` /
    ``MXNET_COMPILE_CACHE_MIN_SECS`` / ``MXNET_COMPILE_CACHE_BUDGET_MB``
    refine it.  See docs/env_vars.md and mxnet_tpu/compile_cache.py.

    The reference pays per-process graph-init cost in milliseconds (its
    kernels are precompiled into libmxnet.so); under XLA a cold llama train
    step is ~2 minutes of compile, so without this every NEW process pays it
    (round-4 verdict: the cache was wired up in bench.py only).
    """
    try:
        from . import compile_cache

        compile_cache.configure()
    except Exception:
        pass  # a cache is an optimization; never break import over it


_honor_compile_cache()

from .base import MXNetError  # noqa: F401
from .context import (  # noqa: F401
    Context, cpu, cpu_pinned, gpu, tpu, num_gpus, num_tpus, current_context,
)
from . import engine  # noqa: F401
from . import sharding  # noqa: F401
from . import layout  # noqa: F401
from .layout import layout_scope, set_default_layout  # noqa: F401
from . import random  # noqa: F401
from . import autograd  # noqa: F401
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from .ndarray import NDArray  # noqa: F401

from .ndarray import waitall  # noqa: F401

from . import initializer  # noqa: F401
from . import initializer as init  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import optimizer  # noqa: F401
from . import kvstore  # noqa: F401
from . import registry  # noqa: F401
from . import metric  # noqa: F401
from . import recordio  # noqa: F401
from . import io  # noqa: F401
from . import image  # noqa: F401
from . import parallel  # noqa: F401
from . import gluon  # noqa: F401
from . import symbol  # noqa: F401
from . import symbol as sym  # noqa: F401
from .symbol import AttrScope  # noqa: F401
from . import model  # noqa: F401
from . import rnn  # noqa: F401
from . import log  # noqa: F401
from . import util  # noqa: F401
from . import name  # noqa: F401
from . import error  # noqa: F401
from . import executor  # noqa: F401
from . import callback  # noqa: F401
from . import module  # noqa: F401
from . import monitor  # noqa: F401
from . import visualization  # noqa: F401
from .monitor import Monitor  # noqa: F401
from . import profiler  # noqa: F401
from . import telemetry  # noqa: F401
from . import compile_cache  # noqa: F401
from . import test_utils  # noqa: F401
from . import amp  # noqa: F401
from . import contrib  # noqa: F401
from . import runtime  # noqa: F401
from . import rtc  # noqa: F401
from . import operator  # noqa: F401
from . import deploy  # noqa: F401
from . import serve  # noqa: F401
from . import library  # noqa: F401
from . import numpy as np  # noqa: F401
from . import numpy_extension as npx  # noqa: F401
from .numpy_extension import set_np, reset_np, is_np_shape, is_np_array  # noqa: F401,E501
