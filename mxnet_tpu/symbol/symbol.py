"""Symbol: declarative graph composition lowered to XLA.

Capability parity with the reference's symbol layer
(``python/mxnet/symbol/symbol.py`` + nnvm Graph/Op registry): compose op
nodes into a DAG, auto-create missing weight/bias variables, infer
shapes/types, serialize to JSON, and bind into an Executor.

TPU-native mechanism: a Symbol's graph *is* the program — ``bind`` emits a
pure jax function evaluated topologically over the node DAG and jits it,
which is exactly the "lower nnvm graph → HLO module → one XLA executable"
north star (the reference instead walks the graph pushing one engine op
per node, ``GraphExecutor::InitCachedOps``,
``src/executor/graph_executor.cc:1220``).  Shape/type inference =
``jax.eval_shape`` over the same function (the reference's
``InferShape/InferType`` passes, ``src/executor/exec_pass.h:238-264``,
cannot disagree with execution here by construction).
"""
from __future__ import annotations

import json
import os
import threading

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ops import registry as _reg
from .. import autograd as _autograd
from .. import random as _random

# op name -> input names that are auxiliary states (mutable, not learnable)
_AUX_INPUTS = {
    "BatchNorm": ("moving_mean", "moving_var"),
    "SyncBatchNorm": ("moving_mean", "moving_var"),
}
# op name -> {aux input name: op output index carrying its updated value}
_AUX_OUTPUTS = {
    "BatchNorm": {"moving_mean": 1, "moving_var": 2},
    "SyncBatchNorm": {"moving_mean": 1, "moving_var": 2},
}

_name_lock = threading.Lock()

_attr_scope = threading.local()


class AttrScope:
    """Attach attributes to every symbol created inside the scope
    (parity: mx.AttrScope, python/mxnet/attribute.py) — the reference's
    manual model-parallel idiom:

        with mx.AttrScope(ctx_group="dev1"):
            h = mx.sym.FullyConnected(x, num_hidden=128)

    Scope attrs are stored dunder-wrapped (``__ctx_group__``) on the
    node so they never collide with operator kwargs; ``bind`` maps
    groups to devices via ``group2ctx``.
    """

    def __init__(self, **attrs):
        self._attrs = {"__%s__" % k: v for k, v in attrs.items()}
        self._prev = None

    @staticmethod
    def current():
        return getattr(_attr_scope, "value", {})

    def __enter__(self):
        self._prev = AttrScope.current()
        merged = dict(self._prev)
        merged.update(self._attrs)
        _attr_scope.value = merged
        return self

    def __exit__(self, *exc):
        _attr_scope.value = self._prev
        return False


def _auto_name(hint, name=None):
    """Resolve a symbol name through the active NameManager
    (``mxnet_tpu.name`` — users install ``Prefix``/custom managers with
    a ``with`` block, reference ``python/mxnet/name.py``).  When no
    manager is installed, the fallback default manager is PROCESS-wide
    (counters shared across threads under ``_name_lock``), so
    auto-names stay unique when graphs built on different threads are
    merged — scoped managers remain thread-local like the reference's."""
    from mxnet_tpu.name import NameManager

    hint = hint.lstrip("_").lower()
    with _name_lock:
        return NameManager.current().get(name, hint)


def _op_attrs(node, mode=None):
    """Operator kwargs for a node: node attrs minus reserved dunder meta
    attrs (AttrScope ctx_group, var shape/dtype, names)."""
    attrs = {k: v for k, v in node.attrs.items()
             if not (k.startswith("__") and k.endswith("__"))}
    if mode is not None:
        attrs["_mode"] = mode
    return attrs


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "_extra")

    def __init__(self, op, name, attrs=None, inputs=(), num_outputs=1):
        self.op = op          # None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs)   # list of (node, out_index)
        self.num_outputs = num_outputs
        self._extra = {}

    @property
    def is_variable(self):
        return self.op is None


class Symbol:
    """An ordered set of graph output entries (parity: symbol.Symbol)."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list of (node, idx)

    # -- identity ---------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group[%d]"
                                % len(self._outputs))

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output %s not found" % index)
            return Symbol([self._outputs[names.index(index)]])
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    # -- arithmetic (parity: symbol operators) ----------------------------
    def __add__(self, other):
        return _binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _binary("broadcast_sub", "_rminus_scalar", self, other)

    def __mul__(self, other):
        return _binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _binary("broadcast_div", "_rdiv_scalar", self, other)

    def __pow__(self, other):
        return _binary("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        return self.__mul__(-1.0)

    # -- graph inspection -------------------------------------------------
    def _topo_nodes(self):
        seen = {}
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for inp, _ in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._outputs:
            visit(node)
        return order

    def list_arguments(self):
        out = []
        aux = set(self.list_auxiliary_states())
        for node in self._topo_nodes():
            if node.is_variable and node.name not in aux:
                out.append(node.name)
        return out

    def list_auxiliary_states(self):
        out = []
        for node in self._topo_nodes():
            if node.is_variable:
                continue
            aux_names = _AUX_INPUTS.get(node.op, ())
            if not aux_names:
                continue
            reg = _reg.get(node.op)
            for nm, (inp, _) in zip(reg.input_names, node.inputs):
                if nm in aux_names and inp.is_variable:
                    out.append(inp.name)
        return out

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            if node.num_outputs == 1:
                names.append(node.name + "_output")
            else:
                names.append("%s_output%d" % (node.name, idx))
        return names

    def list_inputs(self):
        return [n.name for n in self._topo_nodes() if n.is_variable]

    def _aux_update_entries(self):
        """[(aux_var_name, (node, out_idx))]: where each aux state's updated
        value appears among op outputs (train-mode write-back)."""
        out = []
        for node in self._topo_nodes():
            if node.is_variable or node.op not in _AUX_OUTPUTS:
                continue
            mapping = _AUX_OUTPUTS[node.op]
            reg = _reg.get(node.op)
            for nm, (inp, _) in zip(reg.input_names, node.inputs):
                if nm in mapping and inp.is_variable:
                    out.append((inp.name, (node, mapping[nm])))
        return out

    def get_internals(self):
        entries = []
        for node in self._topo_nodes():
            for i in range(node.num_outputs):
                entries.append((node, i))
        return Symbol(entries)

    def list_attr(self):
        if len(self._outputs) == 1:
            return {k: str(v)
                    for k, v in self._outputs[0][0].attrs.items()}
        return {}

    def attr(self, key):
        return self.list_attr().get(key)

    def attr_dict(self):
        return {n.name: {k: str(v) for k, v in n.attrs.items()}
                for n in self._topo_nodes() if n.attrs}

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attrs.update(kwargs)

    # -- composition -------------------------------------------------------
    @staticmethod
    def Group(symbols):
        entries = []
        for s in symbols:
            entries.extend(s._outputs)
        return Symbol(entries)

    # -- evaluation --------------------------------------------------------
    def _make_fn(self, arg_names, mode="predict", group2ctx=None,
                 static_rng=False):
        """Pure function mapping {name: array} -> tuple of outputs.

        ``static_rng=True`` feeds RNG ops a constant key — REQUIRED for
        abstract evaluation (``jax.eval_shape``): drawing from the live
        RNG stream under a trace would leak tracers into global state.
        ``group2ctx`` (group name -> Context) activates the reference's
        manual model-parallel placement: a node carrying an AttrScope
        ``ctx_group`` runs on that group's device, with cross-device
        copies inserted at the boundaries (``device_put`` — exactly the
        reference's cross-dev copy nodes, ``AssignContext``
        graph_executor.cc:1043).  Placement implies eager execution (the
        caller must not jit: one jit = one logical device).
        """
        nodes = self._topo_nodes()
        dev_of = {}
        if group2ctx:
            # EVERY op node gets a device in placement mode: its group's,
            # or the bind context's — so merges of different groups are
            # re-colocated instead of crashing on mixed commitments (the
            # reference's AssignContext copy-node insertion)
            from ..context import current_context

            default_dev = (group2ctx.get(None)
                           or current_context()).jax_device
            for node in nodes:
                if node.is_variable:
                    continue
                grp = node.attrs.get("__ctx_group__")
                ctx = group2ctx.get(grp)
                dev_of[id(node)] = (ctx.jax_device if ctx is not None
                                    else default_dev)

        def fn(bindings):
            vals = {}
            for node in nodes:
                if node.is_variable:
                    if node.name not in bindings:
                        raise MXNetError(
                            "unbound variable %r" % node.name)
                    vals[id(node)] = (bindings[node.name],)
                    continue
                reg = _reg.get(node.op)
                ins = [vals[id(inp)][idx] for inp, idx in node.inputs]
                attrs = _op_attrs(node, mode if reg.needs_mode else None)
                if reg.needs_rng:
                    key = jax.random.PRNGKey(0) if static_rng \
                        else _random.next_key()
                    ins = [key] + ins
                dev = dev_of.get(id(node))
                if dev is not None:
                    ins = [jax.device_put(v, dev) for v in ins]
                out = reg.forward(*ins, **attrs)
                vals[id(node)] = out if isinstance(out, tuple) else (out,)
            return tuple(vals[id(node)][idx]
                         for node, idx in self._outputs)

        return fn

    def eval_imperative(self, bindings):
        """Evaluate with NDArray bindings → list of NDArrays (SymbolBlock)."""
        from ..ndarray.ndarray import NDArray
        from ..context import current_context

        mode = "train" if _autograd.is_training() else "predict"
        fn = self._make_fn(list(bindings), mode=mode)
        datas = {k: (v.data() if isinstance(v, NDArray) else jnp.asarray(v))
                 for k, v in bindings.items()}
        outs = fn(datas)
        return [NDArray(o, ctx=current_context()) for o in outs]

    def eval(self, ctx=None, **kwargs):
        return self.eval_imperative(kwargs)

    # -- verification ------------------------------------------------------
    def lint(self, arg_dtypes=None, **arg_shapes):
        """GS5xx graph verification: per-node shape/dtype propagation that
        blames failures on the offending node (see
        ``mxnet_tpu/analysis/graph_verify.py``).  Returns a list of
        ``Finding``s — empty means the graph is well-formed given the
        supplied shapes::

            sym.lint(data=(8, 10))          # shapes as kwargs
            sym.lint(arg_dtypes={"data": "float16"}, data=(8, 10))

        Also runs automatically as a bind/simple_bind pre-flight when
        ``MXNET_GRAPH_VERIFY=1``.
        """
        from ..analysis.graph_verify import verify_symbol

        return verify_symbol(self, arg_shapes=arg_shapes,
                             arg_dtypes=arg_dtypes)

    # -- inference ---------------------------------------------------------
    @property
    def shape(self):
        """Static output shape (single-output symbols), inferred from the
        ``shape=`` attributes attached to the graph's variables.

        Makes ``hybrid_forward`` code that reads ``x.shape`` traceable
        with Symbol inputs (gluon symbolic trace, ONNX export) — the
        TPU-native stance that shapes are static makes this well-defined.
        """
        if len(self._outputs) != 1:
            raise MXNetError("shape: symbol has %d outputs"
                             % len(self._outputs))
        cached = getattr(self, "_cached_shape", None)
        if cached is not None:
            return cached
        _, out_shapes, _ = self._infer_shape_impl(True)
        if not out_shapes or out_shapes[0] is None:
            raise MXNetError(
                "shape: underdetermined — attach shape= to input vars")
        self._cached_shape = tuple(out_shapes[0])
        return self._cached_shape

    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        # shapes attached at var() creation seed the inference (explicit
        # args/kwargs override them)
        for node in self._topo_nodes():
            if node.is_variable and "__shape__" in node.attrs:
                shp = tuple(node.attrs["__shape__"])
                if all(d != 0 for d in shp):  # 0 dims = deferred/unknown
                    known[node.name] = shp
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = shape
        known.update({k: v for k, v in kwargs.items() if v is not None})
        solved = _solve_shapes(self, known, partial)
        if solved is None:
            return None, None, None
        arg_shapes = [solved.get(n) for n in arg_names]
        aux_shapes = [solved.get(n) for n in aux_names]
        out_shapes = solved["__outputs__"]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        known = dict(zip(arg_names, args)) if args else dict(kwargs)
        shapes = {}
        # types need shapes too for eval_shape: use dummy 1-element shapes
        # when unknown; dtype propagation doesn't depend on them.
        sd = {}
        for n in self.list_inputs():
            dt = known.get(n, _np.float32)
            sd[n] = jax.ShapeDtypeStruct((1,) * 4, _np.dtype(dt))
        try:
            fn = self._make_fn(list(sd), static_rng=True)
            outs = jax.eval_shape(fn, sd)
            out_types = [o.dtype for o in outs]
        except Exception:
            out_types = [_np.float32] * len(self._outputs)
        arg_types = [_np.dtype(known.get(n, _np.float32))
                     for n in arg_names]
        aux_types = [_np.float32] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # -- serialization -----------------------------------------------------
    def tojson(self):
        nodes = self._topo_nodes()
        index = {id(n): i for i, n in enumerate(nodes)}
        def enc_attr(k, v):
            if isinstance(v, str):
                return v
            try:
                return json.dumps(v)
            except TypeError:
                # non-JSON attr values: Initializer objects round-trip
                # via dumps() ('["constant", {"value": 3.0}]'), which
                # load-side create() parses back with its kwargs
                if hasattr(v, "dumps"):
                    return v.dumps()
                return json.dumps(type(v).__name__.lower())

        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": n.op or "null",
                "name": n.name,
                "attrs": {k: enc_attr(k, v) for k, v in n.attrs.items()},
                "inputs": [[index[id(inp)], idx, 0]
                           for inp, idx in n.inputs],
            })
        heads = [[index[id(n)], idx, 0] for n, idx in self._outputs]
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": [i for i, n in enumerate(nodes)
                          if n.is_variable],
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10700],
                      "framework": ["str", "mxnet_tpu"]},
        }, indent=2)

    def save(self, fname):
        from ..base import atomic_path

        # atomic: never leave a half-written symbol.json next to a
        # loadable .params file (docs/fault_tolerance.md)
        with atomic_path(fname) as tmp:
            with open(tmp, "w") as f:
                f.write(self.tojson())

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from .executor import Executor

        if _graph_verify_enabled():
            _preflight_verify(self, kwargs, type_dict)
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None or any(s is None for s in arg_shapes):
            raise MXNetError(
                "simple_bind could not infer all argument shapes from %s"
                % kwargs)
        from .. import ndarray as nd

        args = {n: nd.zeros(s) for n, s in zip(self.list_arguments(),
                                               arg_shapes)}
        auxs = {n: nd.zeros(s) for n, s in
                zip(self.list_auxiliary_states(), aux_shapes)}
        return Executor(self, ctx, args, auxs, grad_req,
                        group2ctx=group2ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        aux_names = self.list_auxiliary_states()
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        if _graph_verify_enabled():
            bound = dict(args or {})
            bound.update(aux_states or {})
            shapes = {k: tuple(v.shape) for k, v in bound.items()
                      if hasattr(v, "shape")}
            dtypes = {k: v.dtype for k, v in bound.items()
                      if hasattr(v, "dtype")}
            _preflight_verify(self, shapes, dtypes)
        return Executor(self, ctx, args or {}, aux_states or {}, grad_req,
                        args_grad=args_grad, group2ctx=group2ctx)


def _graph_verify_enabled():
    """MXNET_GRAPH_VERIFY=1 turns on the GS5xx bind/simple_bind pre-flight
    (docs/env_vars.md)."""
    return os.environ.get("MXNET_GRAPH_VERIFY", "").lower() \
        in ("1", "true", "yes", "on")


def _preflight_verify(sym, arg_shapes, arg_dtypes):
    """Run GS5xx over the graph before building the Executor; raise on
    error-severity findings so a bad graph fails with per-node blame
    instead of a whole-graph eval_shape traceback.  Warn-severity
    findings (e.g. GS504 dead arguments, which bind tolerates) don't
    block."""
    from ..analysis.graph_verify import verify_symbol

    findings = verify_symbol(sym, arg_shapes=arg_shapes,
                             arg_dtypes=arg_dtypes)
    errors = [f for f in findings if f.severity == "error"]
    if errors:
        raise MXNetError(
            "graph verification failed (MXNET_GRAPH_VERIFY=1):\n"
            + "\n".join(str(f) for f in errors))


def _solve_shapes(sym, known, partial):
    """Shape inference via jax.eval_shape with iterative unknown-resolution.

    Unknown input shapes can't generally be solved backwards (XLA infers
    forward); reference parity cases (weights of FC/conv given data shape)
    are handled by the op's shape-hint when available.
    """
    input_names = sym.list_inputs()
    missing = [n for n in input_names if n not in known]
    if missing:
        hinted = _hint_missing(sym, dict(known), missing)
        if hinted is None:
            if partial:
                hinted = dict(known)
            else:
                raise MXNetError(
                    "infer_shape: cannot infer %s from given inputs"
                    % _blame(sym, missing))
        known = hinted
        missing = [n for n in input_names if n not in known]
        if missing and not partial:
            raise MXNetError(
                "infer_shape: unresolved inputs %s" % _blame(sym, missing))
        if missing:
            return {**known, "__outputs__": [None] * len(sym._outputs)}
    dtypes = {}
    for node in sym._topo_nodes():
        if node.is_variable and "__dtype__" in node.attrs:
            dtypes[node.name] = _np.dtype(node.attrs["__dtype__"])
    sd = {n: jax.ShapeDtypeStruct(tuple(known[n]),
                                  dtypes.get(n, _np.float32))
          for n in input_names}
    fn = sym._make_fn(input_names, static_rng=True)
    outs = jax.eval_shape(fn, sd)
    solved = dict(known)
    solved["__outputs__"] = [tuple(o.shape) for o in outs]
    return solved


def _blame(sym, missing):
    """Annotate unresolved input names with their first consumer node
    (shared with the GS502 graph-verify rule); plain list on any
    failure so the original error never gets worse."""
    try:
        from ..analysis.graph_verify import blame_unresolved
        return blame_unresolved(sym, missing)
    except Exception:
        return missing


def _hint_missing(sym, known, missing):
    """Forward-propagate shapes node by node, using per-op weight-shape
    hints (FullyConnected/Convolution/BatchNorm...) to fill parameters."""
    from . import shape_hints

    vals = {}
    for node in sym._topo_nodes():
        if node.is_variable:
            if node.name in known:
                vals[id(node)] = (tuple(known[node.name]),)
            else:
                vals[id(node)] = (None,)
            continue
        in_shapes = []
        names = _reg.get(node.op).input_names
        entries = node.inputs
        shapes_in = [vals[id(inp)][idx] for inp, idx in entries]
        # let the op hint missing variable inputs from the known ones
        hinted = shape_hints.hint(node.op, names, shapes_in, node.attrs)
        if hinted:
            for (inp, idx), s in zip(entries, hinted):
                if s is not None and vals[id(inp)][idx] is None and \
                        inp.is_variable:
                    vals[id(inp)] = (tuple(s),)
                    known[inp.name] = tuple(s)
        shapes_in = [vals[id(inp)][idx] for inp, idx in entries]
        if any(s is None for s in shapes_in):
            return None
        # run eval_shape on this single node
        reg = _reg.get(node.op)
        attrs = _op_attrs(node, "predict" if reg.needs_mode else None)
        def one(*arrs):
            ins = list(arrs)
            if reg.needs_rng:
                ins = [jax.random.PRNGKey(0)] + ins
            out = reg.forward(*ins, **attrs)
            return out if isinstance(out, tuple) else (out,)
        try:
            outs = jax.eval_shape(
                one, *[jax.ShapeDtypeStruct(s, _np.float32)
                       for s in shapes_in])
        except Exception:
            return None
        vals[id(node)] = tuple(tuple(o.shape) for o in outs)
    for n in missing:
        if n not in known:
            return None
    return known


# ---------------------------------------------------------------------------
# construction helpers
# ---------------------------------------------------------------------------
def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (parity: symbol.var)."""
    attrs = dict(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(_np.dtype(dtype))
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        # Initializer object (or registry string): honored by
        # Module.init_params over the global initializer, like the
        # reference's __init__ variable attr
        attrs["__init__"] = init
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, attrs), 0)])


Variable = var


def Group(symbols):
    return Symbol.Group(symbols)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        op = jn["op"]
        attrs = {}
        for k, v in jn.get("attrs", {}).items():
            # tojson stores non-string attrs json-encoded and genuine
            # strings raw, so decoding must try json.loads on every string
            # and keep the raw value when it isn't valid JSON ('relu', …)
            if isinstance(v, str):
                try:
                    attrs[k] = json.loads(v)
                except ValueError:
                    attrs[k] = v
            else:
                attrs[k] = v
        node = _Node(None if op == "null" else op, jn["name"], attrs)
        node.inputs = [(nodes[i], oi) for i, oi, _ in jn["inputs"]]
        if node.op is not None:
            node.num_outputs = _resolved_num_outputs(
                _reg.get(node.op), attrs)
        nodes.append(node)
    heads = [(nodes[i], oi) for i, oi, _ in data["heads"]]
    return Symbol(heads)


def _truthy(v):
    return v in (True, 1, "1", "true", "True")


def _resolved_num_outputs(reg, attrs):
    """Concrete output count: dynamic-output ops (num_outputs<=0, e.g.
    split) take it from their num_outputs attr."""
    return reg.num_outputs if reg.num_outputs > 0 \
        else int(attrs.get("num_outputs", 1))


def _unused_inputs(op_name, attrs):
    """Trailing inputs an op ignores under these attrs (attr-aware
    FListInputNames, reference fully_connected.cc:258 no_bias)."""
    if op_name in ("FullyConnected", "Convolution") \
            and _truthy(attrs.get("no_bias", False)):
        return ("bias",)
    if op_name == "Deconvolution" \
            and _truthy(attrs.get("no_bias", True)):
        return ("bias",)
    if op_name == "softmax" and not _truthy(attrs.get("use_length", False)):
        return ("length",)
    if op_name == "RNN" and attrs.get("mode", "lstm") != "lstm":
        return ("state_cell",)
    return ()


def make_symbol_op(op_name):
    """Build the mx.sym.<op> composition function."""
    reg = _reg.get(op_name)

    def sym_op(*args, **kwargs):
        name = kwargs.pop("name", None)
        kwargs.pop("attr", None)
        # EVERY name routes through the manager (reference semantics:
        # a Prefix scope prefixes user-supplied names too)
        name = _auto_name(op_name, name)
        # split tensor inputs from attrs
        inputs = {}
        pos = list(args)
        n_in = len(reg.input_names)
        for nm, a in zip(reg.input_names, pos[:n_in]):
            if a is not None:
                inputs[nm] = a
        extra = pos[n_in:]
        attrs = {}
        for nm, val in zip(reg.attr_names, extra):
            attrs[nm] = val
        for k, v in list(kwargs.items()):
            if isinstance(v, Symbol):
                inputs[k] = v
            else:
                attrs[k] = v
        if reg.variadic:
            entry_inputs = []
            for a in pos:
                if isinstance(a, Symbol):
                    if len(a._outputs) != 1:
                        entry_inputs.extend(a._outputs)
                    else:
                        entry_inputs.append(a._outputs[0])
            for k, v in AttrScope.current().items():
                attrs.setdefault(k, v)
            n_out = _resolved_num_outputs(reg, attrs)
            node = _Node(op_name, name, attrs, entry_inputs, n_out)
            return Symbol([(node, i) for i in range(n_out)]) \
                if n_out > 1 else Symbol([(node, 0)])
        # auto-create missing trailing variable inputs (weights etc.),
        # except inputs the op ignores under the given attrs (e.g. bias
        # under no_bias=1 — the reference's FListInputNames is attr-aware)
        skip = _unused_inputs(op_name, attrs)
        entries = []
        aux_names = _AUX_INPUTS.get(op_name, ())
        for nm in reg.input_names:
            if nm in skip and nm not in inputs:
                continue
            if nm in inputs:
                s = inputs[nm]
                if not isinstance(s, Symbol):
                    raise MXNetError(
                        "input %s of %s must be a Symbol" % (nm, op_name))
                if len(s._outputs) != 1:
                    raise MXNetError(
                        "input %s of %s must be a single-output Symbol"
                        % (nm, op_name))
                entries.append(s._outputs[0])
            else:
                vnode = _Node(None, "%s_%s" % (name, nm), {})
                entries.append((vnode, 0))
        for k, v in AttrScope.current().items():
            attrs.setdefault(k, v)
        n_out = _resolved_num_outputs(reg, attrs)
        node = _Node(op_name, name, attrs, entries, n_out)
        if n_out > 1:
            return Symbol([(node, i) for i in range(n_out)])
        return Symbol([(node, 0)])

    sym_op.__name__ = op_name
    sym_op.__doc__ = reg.doc
    return sym_op


def _binary(broadcast_op, scalar_op, lhs, rhs):
    if isinstance(rhs, Symbol):
        return make_symbol_op(broadcast_op)(lhs, rhs)
    return make_symbol_op(scalar_op)(lhs, scalar=float(rhs))


def zeros(shape, dtype=None, name=None):
    return make_symbol_op("_zeros")(shape=shape, dtype=dtype or "float32",
                                    name=name)


def ones(shape, dtype=None, name=None):
    return make_symbol_op("_ones")(shape=shape, dtype=dtype or "float32",
                                   name=name)


def arange(start, stop=None, step=1.0, dtype=None, name=None):
    return make_symbol_op("arange")(start=start, stop=stop, step=step,
                                    dtype=dtype or "float32", name=name)
