"""Executor: a bound Symbol compiled to XLA executables.

Capability parity with the reference executor
(``include/mxnet/executor.h:143``, ``GraphExecutor``,
``src/executor/graph_executor.cc:393``): holds argument/gradient/aux
arrays, ``forward(is_train)``, ``backward(out_grads)``, shared-memory
``reshape``, monitor callback.

TPU-native mechanism: ONE jitted callable for forward
(args, auxs, key) → (outputs, new_auxs) per mode, and one for
forward+vjp when training — replacing the reference's per-node engine op
chain (``InitCachedOps``, ``graph_executor.cc:1220``) and memory plan
(``MXPlanMemory``; XLA's buffer assignment does this).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd
from .. import random as _random
from ..context import current_context
from ..ops import registry as _reg


class Executor:
    """Parity: mxnet.executor.Executor (python/mxnet/executor.py)."""

    def __init__(self, symbol, ctx, args, auxs, grad_req="write",
                 args_grad=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        # manual model-parallel placement (AttrScope ctx_group): devices
        # per group imply EAGER per-node execution with cross-device
        # copies — one jit targets one logical device
        self._group2ctx = dict(group2ctx) if group2ctx else None
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()
        missing = [n for n in self.arg_names if n not in args]
        if missing:
            raise MXNetError("bind: missing arguments %s" % missing)
        self.arg_dict = {n: args[n] for n in self.arg_names}
        self.aux_dict = {n: auxs[n] for n in self.aux_names}
        self.arg_arrays = [self.arg_dict[n] for n in self.arg_names]
        self.aux_arrays = [self.aux_dict[n] for n in self.aux_names]
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self.arg_names}
        if args_grad is None:
            self.grad_dict = {
                n: nd.zeros(self.arg_dict[n].shape)
                for n in self.arg_names
                if self._grad_req.get(n, "null") != "null"}
        elif isinstance(args_grad, (list, tuple)):
            self.grad_dict = dict(zip(self.arg_names, args_grad))
        else:
            self.grad_dict = dict(args_grad)
        self.grad_arrays = [self.grad_dict.get(n) for n in self.arg_names]
        self.outputs = []
        self._fns = {}
        self._vjp = None
        self._monitor = None
        self._aux_update_names = [
            n for n, _ in symbol._aux_update_entries()]
        self._grad_input_names = [
            n for n in self.arg_names
            if self._grad_req.get(n, "null") != "null"]

    # -- compiled callables -------------------------------------------------
    def _extended_symbol(self):
        """Symbol whose outputs are (user outputs) + (updated aux values)."""
        from .symbol import Symbol

        aux_entries = self._symbol._aux_update_entries()
        return Symbol(self._symbol._outputs + [e for _, e in aux_entries])

    def _get_fn(self, mode):
        fn = self._fns.get(mode)
        if fn is None:
            ext = self._extended_symbol()
            input_names = ext.list_inputs()
            raw = ext._make_fn(input_names, mode=mode,
                               group2ctx=self._group2ctx)

            def run(key, args, auxs):
                with _random.trace_key_scope(key):
                    bindings = {}
                    bindings.update(args)
                    bindings.update(auxs)
                    return raw(bindings)

            fn = run if self._group2ctx else jax.jit(run)
            self._fns[mode] = fn
        return fn

    def _get_train_fn(self):
        """Jitted train-mode forward: (key, grad_args, other, auxs) → outs."""
        fn = self._fns.get("train_grad")
        if fn is None:
            ext = self._extended_symbol()
            raw = ext._make_fn(ext.list_inputs(), mode="train",
                               group2ctx=self._group2ctx)

            def run(key, grad_args, other_args, auxs):
                with _random.trace_key_scope(key):
                    bindings = dict(other_args)
                    bindings.update(auxs)
                    bindings.update(grad_args)
                    return raw(bindings)

            fn = run if self._group2ctx else jax.jit(run)
            self._fns["train_grad"] = fn
        return fn

    def _get_bwd_fn(self):
        """One jitted executable computing forward+vjp.

        The forward is rematerialized inside the backward executable (the
        TPU-favoured memory/compute trade; XLA fuses and shares what it
        can) so no un-jittable vjp closure ever crosses a call boundary.
        Same key as the forward call → identical dropout/rng draws.
        """
        fn = self._fns.get("train_bwd")
        if fn is None:
            ext = self._extended_symbol()
            raw = ext._make_fn(ext.list_inputs(), mode="train",
                               group2ctx=self._group2ctx)

            def run_bwd(key, grad_args, other_args, auxs, cts):
                def wrt(ga):
                    with _random.trace_key_scope(key):
                        bindings = dict(other_args)
                        bindings.update(auxs)
                        bindings.update(ga)
                        return tuple(raw(bindings))

                _, vjp = jax.vjp(wrt, grad_args)
                (grads,) = vjp(tuple(cts))
                return grads

            fn = run_bwd if self._group2ctx else jax.jit(run_bwd)
            self._fns["train_bwd"] = fn
        return fn

    # -- API ---------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown argument %r" % k)
            self.arg_dict[k] = v if isinstance(v, NDArray) else nd.array(v)
        for i, n in enumerate(self.arg_names):
            self.arg_arrays[i] = self.arg_dict[n]
        args = {n: a.data() for n, a in self.arg_dict.items()}
        auxs = {n: a.data() for n, a in self.aux_dict.items()}
        key = _random.next_key()
        if is_train:
            grad_names = self._grad_input_names
            grad_args = {n: args[n] for n in grad_names}
            other = {n: v for n, v in args.items()
                     if n not in set(grad_names)}
            outs = self._get_train_fn()(key, grad_args, other, auxs)
            self._vjp = ((key, grad_args, other, auxs),
                         [o.dtype for o in outs],
                         [o.shape for o in outs])
        else:
            outs = self._get_fn("predict")(key, args, auxs)
            self._vjp = None
        # split user outputs from updated aux values and write the latter
        n_user = len(self._symbol._outputs)
        for name, val in zip(self._aux_update_names, outs[n_user:]):
            self.aux_dict[name]._set_data(val)
        outs = outs[:n_user]
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        if self._monitor is not None:
            for name, arr in zip(self.output_names, self.outputs):
                self._monitor(name, arr)
        return self.outputs

    def backward(self, out_grads=None, retain_graph=False):
        if self._vjp is None:
            raise MXNetError("backward called before forward(is_train=True)")
        (key, grad_args, other, auxs), dtypes, shapes = self._vjp
        n_user = len(self._symbol._outputs)
        if out_grads is None:
            cts = [jnp.ones(s, d)
                   for s, d in zip(shapes[:n_user], dtypes[:n_user])]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = [(g.data() if isinstance(g, NDArray)
                    else jnp.asarray(g)).astype(d)
                   for g, d in zip(out_grads, dtypes)]
        # zero cotangents for the appended aux-update outputs
        cts = tuple(cts + [jnp.zeros(s, d) for s, d in
                           zip(shapes[n_user:], dtypes[n_user:])])
        grads = self._get_bwd_fn()(key, grad_args, other, auxs, cts)
        for n, g in grads.items():
            req = self._grad_req.get(n, "null")
            dst = self.grad_dict.get(n)
            if dst is None or req == "null":
                continue
            if req == "add":
                dst._set_data(dst.data() + g)
            else:
                dst._set_data(g)

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        new_args = {}
        for n, arr in self.arg_dict.items():
            if n in kwargs:
                new_args[n] = nd.zeros(kwargs[n])
            else:
                new_args[n] = arr
        return Executor(self._symbol, self._ctx, new_args,
                        dict(self.aux_dict), self._grad_req)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for n, v in arg_params.items():
            if n in self.arg_dict:
                self.arg_dict[n][:] = v
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %r" % n)
        if aux_params:
            for n, v in aux_params.items():
                if n in self.aux_dict:
                    self.aux_dict[n][:] = v
                elif not allow_extra_params:
                    raise MXNetError("unknown aux state %r" % n)

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor = callback

    @property
    def output_dict(self):
        return dict(zip(self.output_names, self.outputs))
