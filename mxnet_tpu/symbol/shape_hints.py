"""Per-op weight-shape hints for symbol shape inference.

The reference's ``FInferShape`` attributes solve parameter shapes backwards
from data shapes (e.g. ``FullyConnectedShape``,
``src/operator/nn/fully_connected.cc``).  XLA only infers forwards, so the
few parameterized ops that need backwards solving declare a hint here;
everything else is solved by ``jax.eval_shape`` forward propagation.
"""
from __future__ import annotations


def _as_tuple(v, n=None):
    if isinstance(v, int):
        return (v,) * (n or 1)
    return tuple(v)


def hint(op, input_names, shapes, attrs):
    """Return per-input shapes (or None) given known ones; None = no hint."""
    fn = _HINTS.get(op)
    if fn is None:
        return None
    known = dict(zip(input_names, shapes))
    out = fn(known, attrs)
    if out is None:
        return None
    return [out.get(nm) for nm in input_names]


def _fully_connected(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    num_hidden = int(attrs.get("num_hidden", 0))
    flatten = attrs.get("flatten", True)
    in_units = 1
    if flatten:
        for d in data[1:]:
            in_units *= d
    else:
        in_units = data[-1]
    out = {"weight": (num_hidden, in_units)}
    if not attrs.get("no_bias", False):
        out["bias"] = (num_hidden,)
    return out


def _convolution(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    kernel = _as_tuple(attrs.get("kernel", ()))
    num_filter = int(attrs.get("num_filter", 0))
    num_group = int(attrs.get("num_group", 1))
    in_c = data[1]
    out = {"weight": (num_filter, in_c // num_group) + kernel}
    if not attrs.get("no_bias", False):
        out["bias"] = (num_filter,)
    return out


def _deconvolution(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    kernel = _as_tuple(attrs.get("kernel", ()))
    num_filter = int(attrs.get("num_filter", 0))
    num_group = int(attrs.get("num_group", 1))
    in_c = data[1]
    out = {"weight": (in_c, num_filter // num_group) + kernel}
    if not attrs.get("no_bias", True):
        out["bias"] = (num_filter,)
    return out


def _batch_norm(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    axis = int(attrs.get("axis", 1))
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,),
            "moving_mean": (c,), "moving_var": (c,)}


def _norm_1d(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    axis = int(attrs.get("axis", -1))
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,)}


def _instance_norm(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    return {"gamma": (data[1],), "beta": (data[1],)}


def _embedding(known, attrs):
    input_dim = int(attrs.get("input_dim", 0))
    output_dim = int(attrs.get("output_dim", 0))
    if not input_dim or not output_dim:
        return None
    return {"weight": (input_dim, output_dim)}


def _rnn(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    from ..ops.nn import rnn_param_size

    from .symbol import _truthy

    state_size = int(attrs.get("state_size", 0))
    num_layers = int(attrs.get("num_layers", 1))
    mode = attrs.get("mode", "lstm")
    bidir = _truthy(attrs.get("bidirectional", False))
    if not state_size:
        return None
    n = rnn_param_size(mode, num_layers, data[-1], state_size, bidir)
    dirs = 2 if bidir else 1
    out = {"parameters": (n,),
           "state": (num_layers * dirs, data[1], state_size)}
    if mode == "lstm":
        out["state_cell"] = (num_layers * dirs, data[1], state_size)
    return out


def _loss_label_like_batch(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    # softmax-style losses accept (B,) labels; predict-mode binds without
    # a label feed still need a shape for the unused input
    return {"label": (data[0],)}


def _loss_label_like_data(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    return {"label": tuple(data)}


_HINTS = {
    "RNN": _rnn,
    "SoftmaxOutput": _loss_label_like_batch,
    "SVMOutput": _loss_label_like_batch,
    "LinearRegressionOutput": _loss_label_like_data,
    "MAERegressionOutput": _loss_label_like_data,
    "LogisticRegressionOutput": _loss_label_like_data,
    "FullyConnected": _fully_connected,
    "Convolution": _convolution,
    "Deconvolution": _deconvolution,
    "BatchNorm": _batch_norm,
    "SyncBatchNorm": _batch_norm,
    "LayerNorm": _norm_1d,
    "RMSNorm": lambda known, attrs: (
        {"gamma": (known["data"][int(attrs.get("axis", -1))
                                 % len(known["data"])],)}
        if known.get("data") else None),
    "InstanceNorm": _instance_norm,
    "GroupNorm": _instance_norm,
    "Embedding": _embedding,
}
