"""Per-op weight-shape hints for symbol shape inference.

The reference's ``FInferShape`` attributes solve parameter shapes backwards
from data shapes (e.g. ``FullyConnectedShape``,
``src/operator/nn/fully_connected.cc``).  XLA only infers forwards, so the
few parameterized ops that need backwards solving declare a hint here;
everything else is solved by ``jax.eval_shape`` forward propagation.
"""
from __future__ import annotations


def _as_int(v, default=0):
    """Attr int that may arrive as a string (load_json keeps '3' raw when
    it round-tripped through a user-edited JSON)."""
    if v is None:
        return default
    if isinstance(v, str):
        return int(float(v))
    return int(v)


def _as_tuple(v, n=None):
    """Attr tuple that may arrive as an int, an iterable, or a string
    form like '(3, 3)' / '[3, 3]' / '3' from serialized graphs."""
    if isinstance(v, int):
        return (v,) * (n or 1)
    if isinstance(v, str):
        body = v.strip().strip("()[]")
        if not body:
            return ()
        return tuple(int(float(p)) for p in body.split(",") if p.strip())
    return tuple(int(d) for d in v)


def _flag(v, default=False):
    """Attr bool that may arrive as a string ('True'/'1'/'false')."""
    if v is None:
        return default
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes", "on")
    return bool(v)


def hint(op, input_names, shapes, attrs):
    """Return per-input shapes (or None) given known ones; None = no hint."""
    fn = _HINTS.get(op)
    if fn is None:
        return None
    known = dict(zip(input_names, shapes))
    out = fn(known, attrs)
    if out is None:
        return None
    return [out.get(nm) for nm in input_names]


def _fully_connected(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    num_hidden = _as_int(attrs.get("num_hidden"))
    flatten = _flag(attrs.get("flatten"), True)
    in_units = 1
    if flatten:
        for d in data[1:]:
            in_units *= d
    else:
        in_units = data[-1]
    out = {"weight": (num_hidden, in_units)}
    if not _flag(attrs.get("no_bias")):
        out["bias"] = (num_hidden,)
    return out


def _convolution(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    kernel = _as_tuple(attrs.get("kernel", ()))
    num_filter = _as_int(attrs.get("num_filter"))
    num_group = _as_int(attrs.get("num_group"), 1)
    in_c = data[1]
    out = {"weight": (num_filter, in_c // num_group) + kernel}
    if not _flag(attrs.get("no_bias")):
        out["bias"] = (num_filter,)
    return out


def _deconvolution(known, attrs):
    kernel = _as_tuple(attrs.get("kernel", ()))
    num_filter = _as_int(attrs.get("num_filter"))
    num_group = _as_int(attrs.get("num_group"), 1)
    data = known.get("data")
    if data is not None:
        in_c = data[1]
    else:
        # backwards: recover the input-channel count from a known weight
        # (in_c, num_filter // num_group, *kernel) — lets infer_shape
        # run data-shape-free when only parameters are bound
        weight = known.get("weight")
        if weight is None or len(weight) < 2:
            return None
        in_c = weight[0]
        if not num_filter:
            num_filter = weight[1] * num_group
        if not kernel:
            kernel = tuple(weight[2:])
    out = {"weight": (in_c, num_filter // num_group) + kernel}
    if not _flag(attrs.get("no_bias"), True):
        out["bias"] = (num_filter,)
    return out


def _batch_norm(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    axis = int(attrs.get("axis", 1))
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,),
            "moving_mean": (c,), "moving_var": (c,)}


def _norm_1d(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    axis = int(attrs.get("axis", -1))
    c = data[axis % len(data)]
    return {"gamma": (c,), "beta": (c,)}


def _instance_norm(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    return {"gamma": (data[1],), "beta": (data[1],)}


def _embedding(known, attrs):
    input_dim = _as_int(attrs.get("input_dim"))
    output_dim = _as_int(attrs.get("output_dim"))
    # backwards: a known weight shape (vocab, dim) fills whatever the
    # attrs leave out (deferred-init Gluon blocks carry 0 dims)
    weight = known.get("weight")
    if weight is not None and len(weight) == 2:
        input_dim = input_dim or weight[0]
        output_dim = output_dim or weight[1]
    if not input_dim or not output_dim:
        return None
    return {"weight": (input_dim, output_dim)}


def _rnn(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    from ..ops.nn import rnn_param_size

    from .symbol import _truthy

    state_size = int(attrs.get("state_size", 0))
    num_layers = int(attrs.get("num_layers", 1))
    mode = attrs.get("mode", "lstm")
    bidir = _truthy(attrs.get("bidirectional", False))
    if not state_size:
        return None
    n = rnn_param_size(mode, num_layers, data[-1], state_size, bidir)
    dirs = 2 if bidir else 1
    out = {"parameters": (n,),
           "state": (num_layers * dirs, data[1], state_size)}
    if mode == "lstm":
        out["state_cell"] = (num_layers * dirs, data[1], state_size)
    return out


def _loss_label_like_batch(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    # softmax-style losses accept (B,) labels; predict-mode binds without
    # a label feed still need a shape for the unused input
    return {"label": (data[0],)}


def _loss_label_like_data(known, attrs):
    data = known.get("data")
    if data is None:
        return None
    return {"label": tuple(data)}


_HINTS = {
    "RNN": _rnn,
    "SoftmaxOutput": _loss_label_like_batch,
    "SVMOutput": _loss_label_like_batch,
    "LinearRegressionOutput": _loss_label_like_data,
    "MAERegressionOutput": _loss_label_like_data,
    "LogisticRegressionOutput": _loss_label_like_data,
    "FullyConnected": _fully_connected,
    "Convolution": _convolution,
    "Deconvolution": _deconvolution,
    "BatchNorm": _batch_norm,
    "SyncBatchNorm": _batch_norm,
    "LayerNorm": _norm_1d,
    "RMSNorm": lambda known, attrs: (
        {"gamma": (known["data"][int(attrs.get("axis", -1))
                                 % len(known["data"])],)}
        if known.get("data") else None),
    "InstanceNorm": _instance_norm,
    "GroupNorm": _instance_norm,
    "Embedding": _embedding,
}
