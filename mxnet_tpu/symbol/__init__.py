"""Symbolic graph API (parity: ``python/mxnet/symbol/``)."""
from .symbol import (  # noqa: F401
    Symbol, var, Variable, Group, load, load_json, zeros, ones, arange,
    AttrScope,
)
from .executor import Executor  # noqa: F401
from . import symbol as _symbol_mod
from ..ops import registry as _reg

# install every registered op as a symbol-building function (the symbol
# analogue of mx.nd codegen-at-import, reference register.py:116-264)
for _name in _reg.list_ops():
    globals().setdefault(_name, _symbol_mod.make_symbol_op(_name))
del _name


# contrib sub-namespace: ops named _contrib_* surface as sym.contrib.<name>
# (mirror of nd.contrib so hybrid_forward F.contrib.* traces symbolically)
class _ContribNS:
    def __getattr__(self, item):
        fn = globals().get("_contrib_" + item)
        if fn is None:
            raise AttributeError("sym.contrib.%s" % item)
        return fn

    def __dir__(self):
        return sorted(n[len("_contrib_"):] for n in globals()
                      if n.startswith("_contrib_"))


contrib = _ContribNS()
