"""Global RNG state on stateless threefry keys.

Reference: mshadow PRNG streams seeded via ``mx.random.seed``
(``python/mxnet/random.py``, ``src/resource.cc`` kRandom/kParallelRandom).
TPU-native: one process-level threefry key, split per op invocation — every
random op is reproducible given ``seed()`` and the op sequence, and each
compiled executable takes its key as a runtime argument so no recompilation
happens across calls.  Bit-exactness with mshadow streams is explicitly NOT a
goal (SURVEY.md §7 hard-part 6); tests use statistical tolerances.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _st():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state


def seed(seed_state, ctx="all"):
    """Reset the global stream (parity: mx.random.seed)."""
    st = _st()
    st.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    st = _st()
    st.key, sub = jax.random.split(st.key)
    return sub


def current_key():
    return _st().key
