"""Global RNG state on stateless threefry keys.

Reference: mshadow PRNG streams seeded via ``mx.random.seed``
(``python/mxnet/random.py``, ``src/resource.cc`` kRandom/kParallelRandom).
TPU-native: one process-level threefry key, split per op invocation — every
random op is reproducible given ``seed()`` and the op sequence, and each
compiled executable takes its key as a runtime argument so no recompilation
happens across calls.  Bit-exactness with mshadow streams is explicitly NOT a
goal (SURVEY.md §7 hard-part 6); tests use statistical tolerances.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _st():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state


def seed(seed_state, ctx="all"):
    """Reset the global stream (parity: mx.random.seed)."""
    st = _st()
    st.key = jax.random.PRNGKey(int(seed_state))


def next_key():
    st = _st()
    srcs = getattr(st, "trace_sources", None)
    if srcs:
        # Inside a hybridize trace: derive from the traced key argument so the
        # compiled executable takes fresh randomness at run time instead of
        # baking in a constant drawn at trace time.
        srcs[-1], sub = jax.random.split(srcs[-1])
        return sub
    st.key, sub = jax.random.split(st.key)
    return sub


def current_key():
    return _st().key


class trace_key_scope:
    """Scope routing ``next_key`` to splits of ``key`` (hybridize tracing)."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        st = _st()
        if not hasattr(st, "trace_sources"):
            st.trace_sources = []
        st.trace_sources.append(self._key)
        return self

    def __exit__(self, *a):
        _st().trace_sources.pop()
