"""Candidate rule-set enumeration over a mesh.

A candidate is a *pure* description — ordered ``(glob, spec-entries)``
pairs resolved with first-match-wins and the same divisibility
degradation as ``pattern_rule`` — evaluated against plain
``{axis: size}`` dicts so planning needs no devices.  The pattern
tables are imported from ``parallel/tp_rules.py`` (NOT copied): a
``megatron[model]`` candidate resolves to exactly the specs
``megatron_rule(axis="model", mesh=mesh)`` would produce, which is what
makes the planner's pick bitwise-identical to the hand-picked rule.

Enumeration is deterministic: candidates are emitted in a fixed order
(dp first, then per model axis in mesh order), and the planner breaks
score ties by that order — so dp/replication wins whenever sharding
buys nothing (e.g. a small MLP no megatron pattern matches).
"""
from __future__ import annotations

import fnmatch

from ..parallel.tp_rules import (COLUMN_PATTERNS, EMBED_PATTERNS,
                                 ROW_PATTERNS)

__all__ = ["Candidate", "enumerate_candidates"]


class Candidate:
    """One named rule-set: ordered ``(glob, entries)`` pairs.

    ``entries`` is a tuple of PartitionSpec entries (axis name, None,
    or a tuple of names); params matching no pair replicate.
    """

    __slots__ = ("name", "pairs", "description")

    def __init__(self, name, pairs, description):
        self.name = name
        self.pairs = tuple((str(g), tuple(e)) for g, e in pairs)
        self.description = description

    def spec_for(self, pname, shape, axes):
        """Resolve one param: first matching glob wins; a named dim that
        does not divide its axes (or exceeds the rank) degrades the
        whole param to replication — ``pattern_rule`` semantics."""
        for pat, entries in self.pairs:
            if not fnmatch.fnmatch(pname, pat):
                continue
            entries = entries[:len(shape)]
            for d, e in enumerate(entries):
                if e is None:
                    continue
                size = 1
                for name in (e if isinstance(e, tuple) else (e,)):
                    size *= axes.get(name, 0)
                if size <= 0 or shape[d] % size != 0:
                    return ()
            # drop trailing Nones: P("model", None) == P("model")
            while entries and entries[-1] is None:
                entries = entries[:-1]
            return tuple(entries)
        return ()

    def specs(self, params, axes):
        """``{name: entries}`` for a ``[(name, shape, dtype), ...]`` tree."""
        return {name: self.spec_for(name, shape, axes)
                for name, shape, _dtype in params}

    def __repr__(self):
        return "Candidate(%s)" % self.name


def _megatron_pairs(axis, shard_embeddings=True):
    pairs = [(p, (axis, None)) for p in COLUMN_PATTERNS]
    pairs += [(p, (None, axis)) for p in ROW_PATTERNS]
    if shard_embeddings:
        pairs += [(p, (axis, None)) for p in EMBED_PATTERNS]
    return pairs


def enumerate_candidates(axes, data_axis="data"):
    """The deterministic candidate list for a mesh.

    ``axes`` is an ordered ``{axis: size}`` dict (``spmd_cost.
    mesh_axes``).  Every axis other than ``data_axis`` with size > 1 is
    a tensor-parallel assignment variant.
    """
    cands = []
    if axes.get(data_axis, 1) > 1:
        cands.append(Candidate(
            "dp", (),
            "replicate every parameter; batch sharded on %r (grad "
            "all-reduce inside the step)" % data_axis))
    else:
        cands.append(Candidate(
            "replicated", (), "replicate every parameter (no data axis "
            "in this mesh)"))
    for axis, size in axes.items():
        if axis == data_axis or size <= 1:
            continue
        cands.append(Candidate(
            "megatron[%s]" % axis, _megatron_pairs(axis),
            "Megatron column/row pairing on axis %r (qkv/up/gate column,"
            " o/down row, embeddings vocab-sharded)" % axis))
        cands.append(Candidate(
            "megatron[%s]-replicated-embed" % axis,
            _megatron_pairs(axis, shard_embeddings=False),
            "Megatron pairing on axis %r with embedding/head tables "
            "replicated" % axis))
        cands.append(Candidate(
            "embed[%s]" % axis,
            [(p, (axis, None)) for p in EMBED_PATTERNS],
            "vocab-shard only the embedding tables on axis %r" % axis))
    return cands
