"""Auto-parallelism: a cost-model-driven sharding planner (ROADMAP 4).

SNIPPETS.md [3]'s promise — "scales from 8-chip pods to 6,000-chip
superclusters without changing application code" — needs the framework
to CHOOSE the sharding, the way GSPMD/Alpa-style systems derive specs
from a cost model instead of hand annotations.  This package closes
that loop over the PR-10 substrate:

- :mod:`candidates` enumerates rule-sets over a mesh (replicated/dp,
  megatron column-row pairings per model axis, embed-only variants) —
  the same pattern tables ``parallel/tp_rules.py`` ships, so a chosen
  candidate is *spec-identical* to the hand-picked rule (and therefore
  compiles the identical executable: the bitwise-parity contract);
- :func:`plan` scores every candidate with
  ``analysis/spmd_cost.py`` under a device-memory capacity constraint
  (``MXNET_PLANNER_CAPACITY_BYTES``) and returns a deterministic
  :class:`Plan` whose ``explain()`` is the dry-run report.

Three surfaces: ``JitTrainStep(mesh=..., rules="auto")``,
``serve.export_serving_bundle(..., mesh=..., rules="auto")`` (plan
recorded in the bundle meta), and ``tools/mxplan.py`` (plans from an
``{axis: size}`` dict — no devices needed, a laptop can plan a pod).
"""
from __future__ import annotations

from .candidates import Candidate, enumerate_candidates
from .planner import (ENV_CAPACITY, ENV_DRYRUN, Plan,
                      default_capacity_bytes, plan, plan_for_net,
                      plan_serving)

__all__ = [
    "Candidate", "enumerate_candidates",
    "Plan", "plan", "plan_for_net", "plan_serving",
    "default_capacity_bytes", "ENV_CAPACITY", "ENV_DRYRUN",
]
