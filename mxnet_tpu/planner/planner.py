"""Score candidates, pick a plan, explain it.

``plan()`` is pure and deterministic: same param tree + same mesh axes
→ the same chosen specs, byte for byte (the CI determinism contract —
``tools/mxplan.py`` run twice must diff clean).  Scoring is the
uncalibrated α=1 heuristic by default::

    score = resident bytes/device (params + grads + optimizer slots
            + activation estimate)
          + comm_weight × collective bytes/device/step

with ``comm_weight`` overridable through a ``spmd_cost.Calibration``
(fed from measured telemetry).  A candidate over the capacity is
infeasible; if NONE fits, the smallest-footprint candidate is chosen
and the plan says so (``Plan.feasible``) — the same prediction mxlint
SP1001 makes statically.
"""
from __future__ import annotations

import os
import time

from ..analysis import spmd_cost as _cost
from ..base import MXNetError
from .candidates import enumerate_candidates

__all__ = ["ENV_CAPACITY", "ENV_DRYRUN", "Plan", "default_capacity_bytes",
           "plan", "plan_for_net", "plan_serving"]

ENV_CAPACITY = "MXNET_PLANNER_CAPACITY_BYTES"
ENV_DRYRUN = "MXNET_PLANNER_DRYRUN"


def default_capacity_bytes():
    """Per-device memory budget: ``MXNET_PLANNER_CAPACITY_BYTES`` wins;
    otherwise the accelerator's reported limit; None = unconstrained
    (CPU dryruns report no limit)."""
    env = os.environ.get(ENV_CAPACITY)
    if env:
        try:
            return int(env)
        except ValueError:
            raise MXNetError("%s=%r is not an integer byte count"
                             % (ENV_CAPACITY, env))
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        return int(limit) if limit else None
    except Exception:
        return None


def dryrun_enabled():
    v = os.environ.get(ENV_DRYRUN, "")
    return v not in ("", "0", "false", "False")


def _human(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return ("%d%s" % (n, unit) if unit == "B"
                    else "%.1f%s" % (n, unit))
        n /= 1024.0
    return "%d" % n


class Plan:
    """The planner's decision: chosen specs + the predictions behind it.

    ``param_rule`` is the ``fn(name, shape) -> PartitionSpec|None``
    JitTrainStep consumes — a lookup into the chosen spec map, so the
    executed shardings ARE the scored ones.
    """

    __slots__ = ("candidate", "description", "specs", "report", "score",
                 "mesh_axes", "data_axis", "capacity_bytes", "feasible",
                 "alternatives", "plan_seconds")

    def __init__(self, candidate, description, specs, report, score,
                 mesh_axes, data_axis, capacity_bytes, feasible,
                 alternatives, plan_seconds):
        self.candidate = candidate
        self.description = description
        self.specs = specs                  # name -> entries tuple
        self.report = report                # the chosen CostReport
        self.score = score
        self.mesh_axes = dict(mesh_axes)
        self.data_axis = data_axis
        self.capacity_bytes = capacity_bytes
        self.feasible = feasible
        self.alternatives = alternatives    # [(name, score, feasible)]
        self.plan_seconds = plan_seconds

    def param_rule(self, name, shape):
        """The chosen rule-set as a JitTrainStep ``param_rule``."""
        from jax.sharding import PartitionSpec

        entries = self.specs.get(name)
        if not entries:
            return None
        return PartitionSpec(*entries)

    def explain(self):
        """The dry-run report: chosen spec per parameter + predictions."""
        r = self.report
        mesh = "x".join("%s=%d" % kv for kv in self.mesh_axes.items())
        cap = (_human(self.capacity_bytes) if self.capacity_bytes
               else "unconstrained")
        lines = [
            "mxplan: mesh %s (data axis %r), capacity %s"
            % (mesh, self.data_axis, cap),
            "",
            "  %-38s %12s %9s  %s" % ("candidate", "resident/dev",
                                      "comms/step", "verdict"),
        ]
        for name, score, feasible, rep in self.alternatives:
            verdict = "chosen" if name == self.candidate else (
                "ok" if feasible else "over capacity")
            lines.append("  %-38s %12s %9s  %s"
                         % (name, _human(rep.total_bytes_per_device),
                            _human(rep.collective_bytes), verdict))
        lines += ["", "chosen: %s — %s" % (self.candidate,
                                           self.description)]
        if not self.feasible:
            lines.append("WARNING: no candidate fits the %s capacity — "
                         "predicted per-device OOM (SP1001)" % cap)
        lines.append("")
        lines.append("  %-28s %-18s %-22s %s"
                     % ("parameter", "shape", "spec", "bytes/device"))
        for pc in r.params:
            lines.append("  %-28s %-18s %-22s %s"
                         % (pc.name, "x".join(map(str, pc.shape)),
                            pc.spec_str(), _human(pc.per_device_bytes)))
        lines += [
            "",
            "predicted per device: params %s, grads %s, opt state %s, "
            "activations %s" % (_human(r.param_bytes_per_device),
                                _human(r.grad_bytes_per_device),
                                _human(r.opt_bytes_per_device),
                                _human(r.activation_bytes_per_device)),
            "predicted collectives per step: all-reduce %s, all-gather "
            "%s, reduce-scatter %s" % (_human(r.allreduce_bytes),
                                       _human(r.allgather_bytes),
                                       _human(r.reducescatter_bytes)),
            "compile signatures: %d" % r.compile_signatures,
        ]
        return "\n".join(lines)

    def as_dict(self):
        """JSON-stable form (bundle meta, mxplan --format json)."""
        return {
            "candidate": self.candidate,
            "description": self.description,
            "mesh_axes": dict(self.mesh_axes),
            "data_axis": self.data_axis,
            "capacity_bytes": self.capacity_bytes,
            "feasible": self.feasible,
            "score": self.score,
            "specs": {name: [list(e) if isinstance(e, tuple) else e
                             for e in entries]
                      for name, entries in self.specs.items()},
            "report": self.report.as_dict(),
            "alternatives": [
                {"candidate": name, "score": score, "feasible": feasible,
                 "total_bytes_per_device": rep.total_bytes_per_device,
                 "collective_bytes": rep.collective_bytes}
                for name, score, feasible, rep in self.alternatives],
        }


def plan(params, mesh, data_axis="data", capacity_bytes=None,
         step_tokens=None, optimizer_slots=0, candidates=None,
         calibration=None, trainable=None):
    """Choose a rule-set for ``params`` on ``mesh``.  Deterministic.

    ``capacity_bytes=None`` reads :func:`default_capacity_bytes`; pass
    ``0``/negative to force unconstrained.  See ``spmd_cost.
    analyze_params`` for the remaining knobs.
    """
    t0 = time.perf_counter()
    axes = _cost.mesh_axes(mesh)
    norm = _cost._norm_params(params)
    if capacity_bytes is None:
        capacity_bytes = default_capacity_bytes()
    if capacity_bytes is not None and capacity_bytes <= 0:
        capacity_bytes = None
    comm_weight = calibration.comm_weight if calibration else 1.0
    cands = list(candidates) if candidates is not None \
        else enumerate_candidates(axes, data_axis)
    if not cands:
        raise MXNetError("planner needs at least one candidate rule-set")

    scored, seen_specs = [], {}
    for cand in cands:
        specs = cand.specs(norm, axes)
        key = tuple(sorted(specs.items()))
        if key in seen_specs:
            continue        # spec-identical to an earlier candidate
        seen_specs[key] = cand.name
        rep = _cost.analyze_params(
            norm, axes, specs=specs, data_axis=data_axis,
            optimizer_slots=optimizer_slots, step_tokens=step_tokens,
            trainable=trainable)
        score = int(rep.total_bytes_per_device
                    + comm_weight * rep.collective_bytes)
        feasible = (capacity_bytes is None
                    or rep.total_bytes_per_device <= capacity_bytes)
        scored.append((cand, specs, rep, score, feasible))

    pool = [s for s in scored if s[4]]
    any_feasible = bool(pool)
    if not pool:
        # nothing fits: pick the smallest footprint and say so
        pool = sorted(scored,
                      key=lambda s: s[2].total_bytes_per_device)[:1]
    best = min(pool, key=lambda s: (s[3], cands.index(s[0])))
    cand, specs, rep, score, _ = best
    return Plan(
        candidate=cand.name, description=cand.description, specs=specs,
        report=rep, score=score, mesh_axes=axes, data_axis=data_axis,
        capacity_bytes=capacity_bytes,
        feasible=any_feasible,
        alternatives=[(c.name, sc, fe, rp)
                      for c, _sp, rp, sc, fe in scored],
        plan_seconds=time.perf_counter() - t0)


def _net_params(net, sample=None):
    """``[(name, shape, dtype)]`` from a gluon net; a sample batch
    resolves deferred shapes with one throwaway forward."""
    ps = list(net.collect_params().values())
    if any(0 in tuple(p.shape or (0,)) for p in ps) and sample is not None:
        net(*sample) if isinstance(sample, (tuple, list)) else net(sample)
        ps = list(net.collect_params().values())
    return [(p.name, tuple(p.shape),
             str(getattr(p, "dtype", "float32") or "float32"))
            for p in ps]


def plan_for_net(net, mesh, sample=None, **kwargs):
    """:func:`plan` over a gluon net's parameter tree."""
    return plan(_net_params(net, sample), mesh, **kwargs)


def plan_serving(net, geometry, mesh, data_axis="data", **kwargs):
    """The serving-export hook: plan the weight specs AND suggest a KV
    arena spec (KV-heads dim on the first tensor-parallel axis that
    divides them — the canonical placement ``PagedKVArena`` takes).

    Returns a JSON-able dict stored in the bundle meta (``"planner"``
    key), so a sharded server can be brought up with zero live jits AND
    zero hand-written specs.
    """
    pl = plan_for_net(net, mesh, data_axis=data_axis, **kwargs)
    axes = pl.mesh_axes
    kv_spec = [None, None, None, None, None]
    for axis, size in axes.items():
        if axis != data_axis and size > 1 \
                and geometry.num_kv_heads % size == 0:
            kv_spec[3] = axis        # (L, P, page, KV-heads, head-dim)
            break
    doc = pl.as_dict()
    doc["kv_spec"] = kv_spec
    return doc
