"""``mx.npx`` — numpy-extension namespace (parity: python/mxnet/numpy_extension
+ ``npx.set_np`` in python/mxnet/util.py:65).

Operator-style functions (neural-net ops that have no NumPy equivalent)
are the same registry ops as ``mx.nd.*``; because registry results adopt
the class of their first input, calling them on ``mx.np.ndarray``s yields
``mx.np.ndarray``s — no separate op stack.

``set_np`` is a compatibility toggle: zero-dim/zero-size shapes are
always legal here (XLA handles them natively), so the flag only tracks
user intent for API parity (``is_np_shape``/``is_np_array`` report it).
"""
from __future__ import annotations

import threading

from .. import ndarray as _nd

_flags = threading.local()


def _st():
    if not hasattr(_flags, "np_shape"):
        _flags.np_shape = False
        _flags.np_array = False
    return _flags


def set_np(shape=True, array=True):
    """Enable numpy semantics (parity: util.py set_np)."""
    if array and not shape:
        raise ValueError("np_array requires np_shape")
    st = _st()
    st.np_shape, st.np_array = shape, array


def reset_np():
    set_np(False, False)


def is_np_shape():
    return _st().np_shape


def is_np_array():
    return _st().np_array


def set_np_shape(active):
    st = _st()
    prev, st.np_shape = st.np_shape, bool(active)
    return prev


class np_shape:
    """Context manager forcing the np-shape flag (parity: util.np_shape)."""

    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *a):
        set_np_shape(self._prev)


class np_array:
    def __init__(self, active=True):
        self._active = active
        self._prev = None

    def __enter__(self):
        st = _st()
        self._prev = st.np_array
        st.np_array = bool(self._active)
        return self

    def __exit__(self, *a):
        _st().np_array = self._prev


def use_np(func):
    """Decorator running ``func`` under np semantics (parity: util.use_np)."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        st = _st()
        prev = (st.np_shape, st.np_array)
        st.np_shape = st.np_array = True
        try:
            return func(*args, **kwargs)
        finally:
            st.np_shape, st.np_array = prev

    return wrapper


# -- operator namespace: registry ops surfaced for np arrays ----------------
activation = _nd.Activation
batch_norm = _nd.BatchNorm
convolution = _nd.Convolution
deconvolution = _nd.Deconvolution
fully_connected = _nd.FullyConnected
pooling = _nd.Pooling
dropout = _nd.Dropout
embedding = _nd.Embedding
layer_norm = _nd.LayerNorm
group_norm = _nd.GroupNorm
instance_norm = _nd.InstanceNorm
l2_normalization = _nd.L2Normalization
rnn = _nd.RNN
leaky_relu = _nd.LeakyReLU
softmax = _nd.softmax
log_softmax = _nd.log_softmax
sequence_mask = _nd.SequenceMask
topk = _nd.topk
pick = _nd.pick
one_hot = _nd.one_hot
gather_nd = _nd.gather_nd
scatter_nd = _nd.scatter_nd
reshape_like = _nd.reshape_like
arange_like = _nd.contrib.arange_like
batch_dot = _nd.batch_dot
smooth_l1 = _nd.smooth_l1
sigmoid = _nd.sigmoid
relu = _nd.relu
erf = _nd.erf
erfinv = _nd.erfinv
gamma = _nd.gamma
gammaln = _nd.gammaln
cumsum = _nd.cumsum
foreach = _nd.contrib.foreach
while_loop = _nd.contrib.while_loop
cond = _nd.contrib.cond


def seed(s):
    from .. import random as _random

    _random.seed(s)


def waitall():
    _nd.waitall()
