"""Generic class registry with name/alias lookup and JSON-config creation.

Capability parity with the reference's ``python/mxnet/registry.py``
(``get_register_func``, ``get_alias_func``, ``get_create_func``) — the
mechanism behind ``mx.metric.create('acc')``, ``mx.optimizer.create('adam')``,
``mx.init.Initializer`` registries, etc.
"""
from __future__ import annotations

import json
import warnings

from .base import MXNetError

_REGISTRY = {}


def _registry_for(base_class):
    return _REGISTRY.setdefault(base_class, {})


def get_register_func(base_class, nickname):
    """Make a ``register`` decorator for subclasses of ``base_class``."""
    registry = _registry_for(base_class)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "Can only register subclass of %s" % base_class.__name__
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry:
            warnings.warn(
                "New %s %s.%s registered with name %s is overriding "
                "existing %s %s.%s" % (
                    nickname, klass.__module__, klass.__name__, name,
                    nickname, registry[name].__module__,
                    registry[name].__name__))
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (
        nickname, nickname)
    return register


def get_alias_func(base_class, nickname):
    """Make an ``alias`` decorator registering extra names."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg
    return alias


def get_create_func(base_class, nickname):
    """Make a ``create(name_or_instance_or_json, *args, **kwargs)`` factory."""
    registry = _registry_for(base_class)

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert len(args) == 0 and len(kwargs) == 0, \
                "%s is already an instance. Additional arguments are " \
                "invalid" % nickname
            return name
        if isinstance(name, dict):
            return create(**name)
        assert isinstance(name, str), "%s must be of string type" % nickname
        if name.startswith('['):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
            return create(name, **kwargs)
        if name.startswith('{'):
            assert not args and not kwargs
            kwargs = json.loads(name)
            return create(**kwargs)
        name = name.lower()
        if name not in registry:
            raise MXNetError(
                "%s is not registered. Please register with %s.register "
                "first" % (name, nickname))
        return registry[name](*args, **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create
