"""Runtime lock sanitizer: instrumented proxies for framework locks.

The static CD11xx pass (``mxnet_tpu/analysis/concurrency_check.py``)
reasons about lock *source*; this module watches lock *behaviour*.  With
``MXNET_LOCKCHECK=1`` (or :func:`install`), every framework lock created
through :func:`named_lock` / :func:`named_condition` is wrapped in a
proxy that, per acquisition:

* maintains the calling thread's **held-set** (a stack of lock names),
* adds an edge ``held -> acquiring`` to the process-global
  **acquisition-order graph** and raises :class:`LockCycleError` the
  moment an edge closes a cycle — deadlock *potential* is an error even
  on runs where the interleaving never actually deadlocks,
* counts contention (``mxnet_lock_contention_total{lock}`` — the probe
  acquire failed and the thread had to block) and records a
  ``lock.blocked`` flight event naming the holder,
* observes the hold time into ``mxnet_lock_hold_seconds{lock}`` on
  release.

Cycles additionally record a ``lock.cycle`` flight event before
raising, so a crash dump from a chaos run carries the full cycle path —
the serve-chaos and elastic-chaos CI matrices run under
``MXNET_LOCKCHECK=1`` and assert zero such events in the uploaded dumps.

Design constraints:

* **Zero cost when off.**  Disabled, :func:`named_lock` returns a plain
  ``threading.Lock`` — framework hot paths pay nothing.
* **Import-light** (stdlib + telemetry, like ``faults``): this package
  is imported from ``engine.py`` and ``dist_kvstore.py`` hot paths.
* **Graph nodes are lock NAMES**, not instances: two instances sharing
  a name (e.g. per-key kvstore locks) share one node, so an A→B order
  between *classes* of locks is enforced across all instances.  The
  flip side: same-name edges are skipped (they would be instant false
  cycles), so ordering between two locks of one class is out of scope —
  give locks distinct names where that ordering matters.
* **Proxy transparency**: the proxy supports ``with``, ``acquire`` /
  ``release`` (including ``blocking=False`` and ``timeout=``),
  ``locked()``, and the ``_is_owned`` hook ``threading.Condition``
  probes — ``threading.Condition(named_lock("x"))`` behaves exactly
  like one over a bare lock, with ``wait()`` correctly popping and
  re-pushing the held-set around its internal release/re-acquire.

Enabling mid-process (:func:`install`) affects locks created *after*
the call; module-level framework singletons created at import keep
their bare locks.  ``bench.py``'s lockcheck-overhead probe therefore
constructs a fresh server after ``install()``.
"""
from __future__ import annotations

import threading
import time

from ..base import env_flag
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics

__all__ = [
    "LockCycleError", "enabled", "install", "uninstall", "named_lock",
    "named_rlock", "named_condition", "held", "order_edges", "reset",
]

_ENABLED = env_flag("MXNET_LOCKCHECK", False)

# hold times are expected to be tiny (locks guarding dict/deque state);
# the top buckets exist to make a lock held across a blocking call glow
_HOLD_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
                 1.0, 5.0)

_tls = threading.local()            # .stack: [(proxy, t_acquired), ...]

# the sanitizer's own state is guarded by a BARE lock (never proxied,
# never part of the order graph) and nothing blocking runs under it
_state_lock = threading.Lock()
_edges = {}     # src name -> {dst name: "first seen" description}


class LockCycleError(RuntimeError):
    """A lock acquisition closed a cycle in the acquisition-order graph:
    some interleaving of the participating threads can deadlock, even if
    this run didn't."""


def enabled():
    return _ENABLED


def install():
    """Turn the sanitizer on for locks created from now on."""
    global _ENABLED
    _ENABLED = True


def uninstall():
    """Stop wrapping newly-created locks (existing proxies keep working
    so already-built objects stay consistent)."""
    global _ENABLED
    _ENABLED = False


def reset():
    """Test hook: clear the acquisition-order graph."""
    with _state_lock:
        _edges.clear()


def held():
    """Names of the locks the CURRENT thread holds, outermost first."""
    return [p._name for p, _t in getattr(_tls, "stack", [])]


def order_edges():
    """Snapshot of the acquisition-order graph: ``{src: {dst, ...}}``."""
    with _state_lock:
        return {src: set(dsts) for src, dsts in _edges.items()}


def _find_path(src, dst):
    """BFS over ``_edges`` (caller holds ``_state_lock``); returns the
    name path ``[src, ..., dst]`` or ``None``."""
    frontier = [[src]]
    seen = {src}
    while frontier:
        path = frontier.pop(0)
        for nxt in _edges.get(path[-1], ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(path + [nxt])
    return None


def _describe(path):
    return " -> ".join(path)


def _note_order(proxy):
    """Record ``held -> proxy`` edges; raise on a fresh cycle."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    me = threading.current_thread().name
    new = proxy._name
    for heldp, _t in stack:
        src = heldp._name
        if src == new:
            continue  # same-name nesting: out of scope (see module doc)
        with _state_lock:
            dsts = _edges.setdefault(src, {})
            if new in dsts:
                continue
            back = _find_path(new, src)
            if back is not None:
                fwd = [src, new]
                where = "; ".join(
                    "%s->%s first seen %s" % (a, b, _edges[a][b])
                    for a, b in zip(back, back[1:]))
                _flight.record("lock.cycle", name=new,
                               path=_describe(fwd),
                               conflicts=_describe(back), thread=me)
                raise LockCycleError(
                    "lock-order cycle: thread %r acquires %s while "
                    "holding %s (order %s), but the reverse order %s "
                    "already exists (%s) — some interleaving deadlocks"
                    % (me, new, src, _describe(fwd), _describe(back),
                       where))
            dsts[new] = "thread %s" % me


def _push(proxy):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((proxy, time.monotonic()))


def _pop(proxy):
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] is proxy:
            _p, t0 = stack.pop(i)
            _metrics.histogram(
                "mxnet_lock_hold_seconds",
                help="instrumented-lock hold time (MXNET_LOCKCHECK=1)",
                buckets=_HOLD_BUCKETS,
                lock=proxy._name).observe(time.monotonic() - t0)
            return


class _LockProxy:
    """Instrumented ``threading.Lock`` stand-in (see module docstring)."""

    _reentrant = False

    def __init__(self, name):
        self._name = name
        self._inner = threading.Lock()
        self._owner = None          # thread ident while held
        self._owner_name = None
        self._count = 0

    def acquire(self, blocking=True, timeout=-1):
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            self._count += 1
            return True
        _note_order(self)
        # this IS the lock implementation: release pairs in release(),
        # driven by the caller's with/try-finally  # mxlint: disable=CD1104
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            _metrics.counter(
                "mxnet_lock_contention_total",
                help="instrumented-lock acquisitions that had to block "
                     "(MXNET_LOCKCHECK=1)",
                lock=self._name).inc()
            _flight.record("lock.blocked", name=self._name,
                           holder=self._owner_name or "?",
                           thread=threading.current_thread().name)
            got = self._inner.acquire(True, timeout) if timeout != -1 \
                else self._inner.acquire(True)
            if not got:
                return False
        self._owner = me
        self._owner_name = threading.current_thread().name
        self._count = 1
        _push(self)
        return True

    def release(self):
        if self._reentrant and self._owner == threading.get_ident() \
                and self._count > 1:
            self._count -= 1
            return
        _pop(self)
        self._owner = None
        self._owner_name = None
        self._count = 0
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    # threading.Condition probes this instead of its acquire(0) fallback
    # — without it every wait()/notify() would count spurious contention
    def _is_owned(self):
        return self._owner == threading.get_ident()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<%s %r held=%s>" % (type(self).__name__, self._name,
                                    self._owner is not None)


class _RLockProxy(_LockProxy):
    """Reentrant variant: nested acquires by the owner are counted, only
    the outermost acquisition/release touches the held-set and graph."""

    _reentrant = True


def named_lock(name):
    """A ``threading.Lock`` — instrumented under ``MXNET_LOCKCHECK=1``
    (``name`` labels its telemetry and names its order-graph node)."""
    if not _ENABLED:
        return threading.Lock()
    return _LockProxy(name)


def named_rlock(name):
    if not _ENABLED:
        return threading.RLock()
    return _RLockProxy(name)


def named_condition(name, lock=None):
    """A ``threading.Condition`` over :func:`named_lock` (or over a
    caller-supplied lock/proxy, for conditions sharing one lock)."""
    return threading.Condition(lock if lock is not None
                               else named_lock(name))
