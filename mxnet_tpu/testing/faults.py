"""Deterministic fault injection for the distributed tier and the engine.

Every chaos test in ``tests/test_fault_tolerance.py`` is driven by a
:class:`FaultPlan`: a list of rules saying *where* (injection site),
*when* (match predicates + skip/repeat counters + seeded coin flips) and
*what* (connection reset, truncated frame, delay, raised exception,
server kill) to inject.  The plan is pure data — JSON-serializable, and
loadable from the ``MXNET_FAULT_PLAN`` environment variable (inline JSON
or a path to a JSON file), so a failing CI run reproduces locally from
the plan + seed printed in the log.

Injection sites (each a single ``maybe_inject(site, **ctx)`` call in
framework code; zero cost when no plan is installed):

=================  ==========================================================
site               where / ctx
=================  ==========================================================
``send``           ``dist_kvstore._send`` entry; ctx: ``cmd`` (wire command
                   int), ``sock``, plus the caller's role/rank
``recv``           ``dist_kvstore._recv`` entry; ctx: ``sock``, role/rank
``connect``        ``DistKVStore._sock`` before ``create_connection``;
                   ctx: ``server`` (server id), role/rank
``server_handle``  ``DistServer._handle`` after each decoded frame;
                   ctx: ``cmd``, ``server`` (the DistServer), role
``engine_push``    ``Engine.push`` before running the op; ctx: ``op``
``serve_step``     ``LlamaServer._loop_tick`` before each scheduler round;
                   ctx: ``step`` (loop iteration count) — the site for
                   ``kill_loop`` (crash-containment tests)
``serve_prefill``  ``Scheduler._prefill`` before the runner call; ctx:
                   ``rid``, ``bucket`` — an injected raise fails only
                   that request (slot poisoning path)
``serve_decode``   ``Scheduler._decode_once``/``_verify_once`` before the
                   batched runner call; ctx: ``batch`` — an injected
                   raise fails every active lane
``serve_splice``   ``Scheduler._admit_head_locked`` after a prefix-cache
                   match, before the block-table splice; ctx: ``rid``,
                   ``pages``.  A raising action falls back to the cold
                   prefill path (the hit is abandoned, not the request);
                   ``kill_loop`` here dies with refcounted pages live —
                   containment must free them exactly once
``serve_chunk``    ``Scheduler._chunk_once`` before the batched chunk
                   executable call; ctx: ``batch`` — an injected raise
                   fails every mid-prefill lane
``client_disconnect``  polled once per scheduler step for every queued and
                   in-flight request; ctx: ``rid``, ``tid``.  A raising
                   action is swallowed and turned into
                   ``Request.cancel()`` — the deterministic stand-in for
                   "the client went away"
``fleet_probe``    ``FleetRouter._probe_one`` before calling the replica's
                   healthz; ctx: ``replica``.  A raising action is one
                   failed probe — enough of them in a row trip the
                   per-replica circuit breaker
``fleet_forward``  ``FleetRouter._generate`` after picking a replica,
                   before forwarding; ctx: ``replica``, ``attempt``.  A
                   raising action exercises the retry-on-a-different-
                   replica path
``replica_kill``   ``LocalReplica.submit`` before enqueueing; ctx:
                   ``replica``.  ``kill_loop`` here is the deterministic
                   stand-in for the replica *process* dying: the wrapper
                   routes it through loop-crash containment (in-flight
                   work fails typed, healthz flips sticky not-ok) and
                   raises a transport error to the router
``replica_hang``   ``LocalReplica.submit``; ctx: ``replica``.  A raising
                   action makes the replica swallow the request — it is
                   "accepted" but never completes, the scenario hedging
                   exists for
``replica_slow``   ``LocalReplica.submit``; ctx: ``replica``.  Pair with
                   ``delay`` to model a straggler replica the router
                   should route away from
=================  ==========================================================

Rule fields (all optional except ``site`` and ``action``):

* ``match`` — dict of ctx-key → expected value; the rule only considers
  calls whose ctx matches every entry (missing keys never match).
* ``after`` — skip the first N matching calls (default 0).
* ``times`` — fire at most N times (default 1; ``0``/``null`` = forever).
* ``prob`` — fire with this probability.  The coin flip is derived from
  ``(plan seed, rule index, match ordinal)``, NOT from a shared RNG
  stream, so one rule's decisions are independent of how other rules'
  calls interleave across threads — the same seed always produces the
  same decision for the k-th matching call of a rule.
* ``action`` — one of:

  - ``"reset"``    raise ``ConnectionResetError`` (peer vanished)
  - ``"refuse"``   raise ``ConnectionRefusedError`` (nobody listening)
  - ``"truncate"`` write a partial frame header to ``ctx['sock']``, close
    it, then raise ``ConnectionResetError`` — the peer sees a truncated
    frame, the caller sees a dead socket
  - ``"delay"``    ``time.sleep(rule['delay'])`` then continue normally
  - ``"raise"``    raise :class:`FaultInjected` (``rule['message']``) —
    simulates an op failure / a crashing participant
  - ``"kill_server"`` call ``ctx['server'].shutdown()`` then raise
    ``ConnectionResetError`` — the whole server process "dies" mid-round
  - ``"kill_loop"`` raise :class:`LoopKilled` — simulates the serve
    loop's thread dying mid-step.  The scheduler's per-slot exception
    handlers deliberately re-raise it, so wherever it is injected
    (``serve_step``, ``serve_prefill``, ``serve_decode``) it escapes to
    ``LlamaServer``'s crash containment, which must fail the in-flight
    work with a typed error and restart the loop
  - ``"kill_worker"`` raise :class:`WorkerKilled` carrying the victim's
    ``rank`` (from the thread ctx) and the rule's optional
    ``rejoin_after`` — the elastic-training harness catches it, drops
    the rank out of the round, and (if ``rejoin_after=N`` is set)
    re-admits it N rounds later via ``DistKVStore.join()``; the rule is
    pure data, so the whole kill/rejoin schedule replays from the seed

* ``rejoin_after`` — (``kill_worker`` only) rounds to stay dead before
  the harness re-admits the killed rank; ``null``/absent = stay dead.

Every firing is appended to ``plan.events`` (site, action, rule index,
ordinal, scalar ctx), so a test can assert the *exact* injection
sequence — and that two runs from the same seed produce the same one.
"""
from __future__ import annotations

import json
import os
import threading
import time

from ..telemetry import flight as _flight


class FaultInjected(RuntimeError):
    """Raised by ``action: "raise"`` rules (and used as the marker type
    for injected op failures in ``Engine.push`` chaos tests)."""


class LoopKilled(FaultInjected):
    """Raised by ``action: "kill_loop"``: the serve loop "dies" mid-step.

    The serving tier's per-slot exception handlers re-raise this type
    instead of containing it as a single-request failure, so an injected
    kill always reaches ``LlamaServer``'s loop-level crash containment —
    the path tests/test_serve_chaos.py exercises."""


class WorkerKilled(FaultInjected):
    """Raised by ``action: "kill_worker"``: this worker "dies" mid-round.

    Carries ``rank`` (the victim, from the thread ctx tagged by
    ``set_role``) and ``rejoin_after`` (the rule's re-admission delay in
    rounds, or None) so the chaos harness can schedule a deterministic
    ``DistKVStore.join()`` without re-parsing the plan."""

    def __init__(self, message, rank=None, rejoin_after=None):
        super().__init__(message)
        self.rank = rank
        self.rejoin_after = rejoin_after


_tls = threading.local()


def set_role(role, **extra):
    """Tag the calling thread for rule matching (``role`` plus e.g.
    ``rank``).  ``DistServer._handle`` threads tag themselves
    ``server``; ``DistKVStore`` RPCs tag ``worker`` with their rank."""
    ctx = {"role": role}
    ctx.update(extra)
    _tls.ctx = ctx


def _thread_ctx():
    return getattr(_tls, "ctx", None)


class FaultPlan:
    """A seeded, replayable chaos schedule (see module docstring)."""

    def __init__(self, seed=0, rules=()):
        self.seed = int(seed)
        self.rules = [dict(r) for r in rules]
        for i, r in enumerate(self.rules):
            if "site" not in r or "action" not in r:
                raise ValueError(
                    "fault rule %d needs 'site' and 'action': %r" % (i, r))
        self.events = []
        self._matched = [0] * len(self.rules)  # matching calls seen
        self._fired = [0] * len(self.rules)    # injections performed
        self._lock = threading.Lock()

    # -- (de)serialization --------------------------------------------------
    def to_json(self):
        return json.dumps({"seed": self.seed, "rules": self.rules})

    @classmethod
    def from_json(cls, text):
        cfg = json.loads(text)
        if isinstance(cfg, list):  # bare rule list: seed 0
            cfg = {"rules": cfg}
        return cls(seed=cfg.get("seed", 0), rules=cfg.get("rules", ()))

    # -- deterministic per-rule coin ---------------------------------------
    def _coin(self, rule_idx, ordinal, prob):
        # splitmix64-ish scramble of (seed, rule, ordinal): stable across
        # processes and independent of cross-thread interleaving
        x = (self.seed * 0x9E3779B97F4A7C15
             + rule_idx * 0xBF58476D1CE4E5B9 + ordinal) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 30
        x = (x * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 27
        x = (x * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 31
        return (x / 2.0 ** 64) < prob

    # -- firing -------------------------------------------------------------
    def fire(self, site, ctx):
        """Evaluate every rule against one hook call; perform at most one
        action (the first rule that decides to fire wins)."""
        action = None
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule["site"] != site:
                    continue
                match = rule.get("match")
                if match and any(ctx.get(k) != v for k, v in match.items()):
                    continue
                self._matched[i] += 1
                ordinal = self._matched[i]
                if ordinal <= int(rule.get("after", 0)):
                    continue
                times = rule.get("times", 1)
                if times and self._fired[i] >= int(times):
                    continue
                prob = rule.get("prob")
                if prob is not None and not self._coin(i, ordinal,
                                                       float(prob)):
                    continue
                self._fired[i] += 1
                action = rule
                self.events.append({
                    "site": site, "action": rule["action"], "rule": i,
                    "n": self._fired[i],
                    "ctx": {k: v for k, v in ctx.items()
                            if isinstance(v, (int, float, str, bool))},
                })
                # chaos forensics: the flight dump of a killed process
                # must name what was injected where (docs/observability.md)
                scalars = {k: v for k, v in ctx.items()
                           if isinstance(v, (int, float, str, bool))
                           and k not in ("site", "action", "rule", "n")}
                _flight.record("fault", site=site, action=rule["action"],
                               rule=i, n=self._fired[i], **scalars)
                break
        if action is not None:
            self._perform(action, ctx)

    @staticmethod
    def _perform(rule, ctx):
        act = rule["action"]
        if act == "delay":
            time.sleep(float(rule.get("delay", 0.1)))
            return
        if act == "reset":
            raise ConnectionResetError(
                "fault-injected connection reset (%s)" % rule.get("site"))
        if act == "refuse":
            raise ConnectionRefusedError("fault-injected connection refusal")
        if act == "raise":
            raise FaultInjected(rule.get("message", "fault-injected failure"))
        if act == "truncate":
            sock = ctx.get("sock")
            if sock is not None:
                try:
                    sock.sendall(b"MX")  # half a magic: peer sees EOF mid-frame
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
            raise ConnectionResetError("fault-injected truncated frame")
        if act == "kill_server":
            server = ctx.get("server")
            if server is not None:
                server.shutdown()
            raise ConnectionResetError("fault-injected server kill")
        if act == "kill_loop":
            raise LoopKilled(rule.get("message",
                                      "fault-injected serve-loop kill"))
        if act == "kill_worker":
            rank = ctx.get("rank")
            rejoin = rule.get("rejoin_after")
            raise WorkerKilled(
                "fault-injected worker kill (rank %s%s)"
                % (rank, "" if rejoin is None
                   else ", rejoins after %d round(s)" % int(rejoin)),
                rank=rank, rejoin_after=rejoin)
        raise ValueError("unknown fault action %r" % (act,))


# ---------------------------------------------------------------------------
# global plan registry (explicit install() for tests, env for processes)
# ---------------------------------------------------------------------------

_PLAN = None
_ENV_CACHE = (None, None)  # (raw env string, parsed plan)
_ENV_LOCK = threading.Lock()


def install(plan):
    """Make ``plan`` the process-wide active plan; returns it."""
    global _PLAN
    _PLAN = plan
    return plan


def uninstall():
    """Deactivate any installed plan (env plans reload on next use)."""
    global _PLAN, _ENV_CACHE
    _PLAN = None
    _ENV_CACHE = (None, None)


def current():
    """The active plan: the installed one, else ``MXNET_FAULT_PLAN``
    (inline JSON, or a path to a JSON file), else ``None``."""
    if _PLAN is not None:
        return _PLAN
    raw = os.environ.get("MXNET_FAULT_PLAN")
    if not raw:
        return None
    global _ENV_CACHE
    with _ENV_LOCK:
        cached_raw, cached_plan = _ENV_CACHE
        if raw == cached_raw:
            return cached_plan
        text = raw
        if not raw.lstrip().startswith(("{", "[")):
            with open(raw, encoding="utf-8") as f:
                text = f.read()
        plan = FaultPlan.from_json(text)
        _ENV_CACHE = (raw, plan)
        return plan


def maybe_inject(site, **ctx):
    """Hook point: no-op unless a plan is active (one dict lookup)."""
    plan = _PLAN
    if plan is None and not os.environ.get("MXNET_FAULT_PLAN"):
        return
    plan = current()
    if plan is None:
        return
    tctx = _thread_ctx()
    if tctx:
        merged = dict(tctx)
        merged.update(ctx)
        ctx = merged
    plan.fire(site, ctx)
