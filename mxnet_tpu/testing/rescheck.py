"""Runtime resource-leak sanitizer: a tracked-handle registry.

The static RL12xx pass (``mxnet_tpu/analysis/lifecycle_check.py``)
proves lifecycle properties about handles it can *see* in one function
body; this module watches the handles whose ownership crosses threads
and components — exactly the ones static analysis hands off and stops
tracking.  With ``MXNET_RESCHECK=1`` (or :func:`install`), the
framework's acquisition sites register every expensive handle here:

* arena page lists (``serve/arena.py`` ``alloc``/``free``),
* scheduler request futures (queued ``Request`` objects — resolved,
  failed, or cancelled),
* kvstore client sockets (``parallel/dist_kvstore.py``),
* serve loop threads (``serve/server.py``),
* temp files/dirs (``base.atomic_path``),
* armed flight-dump registrations (``telemetry/flight.arm`` — tracked
  for double-disarm detection but *exempt* from quiescence: a dump
  hook legitimately outlives every drain).

Each registration records kind, owner, a creation-site stack and the
flight sequence number at acquisition.  :func:`release` on an
already-released token raises :class:`ResourceLeakError` (and records
a ``res.double_free`` flight event); :func:`assert_quiescent` — called
from ``LlamaServer.stop()``/``drain()`` and usable from any test —
reports every live handle with its creation stack, generalizing
``PagedKVArena.assert_quiescent()`` from pages-only to every handle
kind.  An atexit hook reports stragglers to stderr (never raising at
interpreter exit).  Telemetry: ``mxnet_resource_live{kind}`` gauge,
``mxnet_resource_leaks_total{kind}`` counter, ``res.leak`` /
``res.double_free`` flight events (the chaos CI matrices run under
``MXNET_RESCHECK=1`` and assert zero ``res.leak`` events in the
uploaded dumps).

Design constraints (same contract as ``lockcheck``):

* **Zero cost when off.**  Disabled, :func:`acquire` returns ``None``
  and :func:`release`/:func:`assert_quiescent` are no-ops on ``None``
  — instrumented hot paths pay one truthiness check.
* **Import-light** (stdlib + telemetry): imported from the serve loop
  and kvstore hot paths.
* **Own state under a BARE lock** (never a framework ``named_lock``;
  nothing blocking runs under it) so the sanitizer can never deadlock
  the code it watches.

Enabling mid-process (:func:`install`) affects handles acquired
*after* the call; ``bench.py``'s rescheck-overhead probe therefore
constructs a fresh server after ``install()``.
"""
from __future__ import annotations

import atexit
import itertools
import sys
import threading
import time
import traceback

from ..base import env_flag
from ..telemetry import flight as _flight
from ..telemetry import metrics as _metrics

__all__ = [
    "ResourceLeakError", "enabled", "install", "uninstall", "reset",
    "acquire", "release", "live", "assert_quiescent",
]

_ENABLED = env_flag("MXNET_RESCHECK", False)

_seq = itertools.count()

# registry of live handles, keyed by token; bare lock per module doc
_state_lock = threading.Lock()
_live = {}          # _Handle -> None (insertion-ordered set)
_leaked_total = 0   # handles ever reported leaked (test/debug aid)


class ResourceLeakError(RuntimeError):
    """A tracked handle was leaked (still live at a quiescence point)
    or released twice.  ``leaks`` carries the offending handles."""

    def __init__(self, message, leaks=()):
        super().__init__(message)
        self.leaks = tuple(leaks)


class _Handle:
    """One live acquisition.  Opaque to callers — hold it, pass it back
    to :func:`release`."""

    __slots__ = ("kind", "owner", "scope", "exempt", "seq", "stack",
                 "released")

    def __init__(self, kind, owner, scope, exempt):
        self.kind = kind
        self.owner = owner
        self.scope = scope
        self.exempt = exempt
        self.seq = next(_seq)
        # skip the two innermost frames (this ctor + acquire)
        self.stack = traceback.extract_stack(sys._getframe(2), limit=6)
        self.released = False

    @property
    def site(self):
        if self.stack:
            f = self.stack[-1]
            return "%s:%d in %s" % (f.filename, f.lineno, f.name)
        return "?"

    def describe(self):
        head = "%s %r (scope=%s, seq=%d) acquired at:" % (
            self.kind, self.owner, self.scope or "-", self.seq)
        frames = "".join("    %s" % line
                         for line in traceback.format_list(self.stack))
        return head + "\n" + frames.rstrip("\n")

    def __repr__(self):
        return "<tracked %s %r live=%s>" % (self.kind, self.owner,
                                            not self.released)


def enabled():
    return _ENABLED


def install():
    """Turn the sanitizer on for handles acquired from now on."""
    global _ENABLED
    _ENABLED = True


def uninstall():
    """Stop tracking newly-acquired handles (handles already tracked
    stay tracked so their release() calls pair up)."""
    global _ENABLED
    _ENABLED = False


def reset():
    """Test hook: forget every tracked handle."""
    with _state_lock:
        for h in _live:
            _gauge(h.kind).dec()
        _live.clear()


def _gauge(kind):
    return _metrics.gauge(
        "mxnet_resource_live",
        help="tracked handles currently live (MXNET_RESCHECK=1)",
        kind=kind)


def _leak_counter(kind):
    return _metrics.counter(
        "mxnet_resource_leaks_total",
        help="tracked handles reported leaked at a quiescence point "
             "(MXNET_RESCHECK=1)",
        kind=kind)


def acquire(kind, owner, scope=None, exempt=False):
    """Register a live handle; returns the token to :func:`release`
    later, or ``None`` when the sanitizer is off.

    ``kind`` buckets the handle for telemetry and filtering (``arena``,
    ``socket``, ``future``, ``thread``, ``tempfile``, ``flight``);
    ``owner`` names the owning entity (request id, server shard, path);
    ``scope`` groups handles torn down together (one server instance,
    one kvstore client) so :func:`assert_quiescent` can check one
    component without tripping over another's live handles.  Exempt
    handles skip quiescence/atexit reporting but keep double-free
    detection.
    """
    if not _ENABLED:
        return None
    h = _Handle(str(kind), str(owner), scope, exempt)
    with _state_lock:
        _live[h] = None
    _gauge(h.kind).inc()
    return h


def release(token):
    """Mark a tracked handle released.  ``None``-tolerant (the token is
    ``None`` whenever the acquire ran with the sanitizer off).  Raises
    :class:`ResourceLeakError` on a second release of the same token —
    the runtime twin of static RL1204."""
    if token is None:
        return
    with _state_lock:
        if token.released:
            double = True
        else:
            double = False
            token.released = True
            _live.pop(token, None)
    if double:
        _flight.record("res.double_free", resource=token.kind,
                       owner=token.owner, site=token.site)
        raise ResourceLeakError(
            "double release of tracked %s %r (first acquired at %s)"
            % (token.kind, token.owner, token.site), leaks=[token])
    _gauge(token.kind).dec()


def live(kind=None, scope=None):
    """Snapshot of live (non-exempt) handles, oldest first."""
    with _state_lock:
        out = [h for h in _live if not h.exempt]
    if kind is not None:
        out = [h for h in out if h.kind == kind]
    if scope is not None:
        out = [h for h in out if h.scope == scope]
    return out


def assert_quiescent(scope=None, kind=None, grace_s=0.25):
    """Raise :class:`ResourceLeakError` naming every live handle (in
    ``scope``/of ``kind``, when given) with its creation stack — the
    every-handle-kind generalization of
    ``PagedKVArena.assert_quiescent``.  Each leak records a
    ``res.leak`` flight event and bumps
    ``mxnet_resource_leaks_total{kind}``.

    ``grace_s`` re-polls briefly before declaring a leak: a resolving
    thread may sit between handing the resource back and releasing its
    token (e.g. the serve loop finishing a slot while ``drain()``
    checks) — a leak is a handle that *stays* live, not one caught
    mid-release."""
    deadline = time.monotonic() + float(grace_s)
    while True:
        leaks = live(kind=kind, scope=scope)
        if not leaks:
            return
        if time.monotonic() >= deadline:
            break
        time.sleep(0.005)
    _report(leaks)
    raise ResourceLeakError(
        "%d tracked handle(s) still live at quiescence point%s:\n%s"
        % (len(leaks),
           " (scope=%s)" % scope if scope is not None else "",
           "\n".join(h.describe() for h in leaks)),
        leaks=leaks)


def _report(leaks):
    global _leaked_total
    for h in leaks:
        _flight.record("res.leak", resource=h.kind, owner=h.owner,
                       scope=h.scope or "-", site=h.site, seq=h.seq)
        _leak_counter(h.kind).inc()
    with _state_lock:
        _leaked_total += len(leaks)


def _atexit_report():
    leaks = live()
    if not leaks:
        return
    _report(leaks)
    # never raise at interpreter exit: leave the evidence on stderr
    # (and in the flight dump, which arms its own atexit/excepthook)
    print("mxnet_tpu: MXNET_RESCHECK: %d tracked handle(s) leaked at "
          "exit:\n%s" % (len(leaks),
                         "\n".join(h.describe() for h in leaks)),
          file=sys.stderr)


atexit.register(_atexit_report)
