"""Deterministic test harnesses for the framework itself.

Import-light by design (stdlib only at import time): ``engine.py`` and
``parallel/dist_kvstore.py`` import :mod:`mxnet_tpu.testing.faults` on
their hot paths, so this package must never pull in jax/numpy.

* ``faults`` — seeded, replayable fault injection for the distributed
  tier and the engine (``FaultPlan``, ``MXNET_FAULT_PLAN``).  See
  ``docs/fault_tolerance.md``.
* ``lockcheck`` — the runtime lock sanitizer (``MXNET_LOCKCHECK=1``):
  instrumented proxies for the framework's named locks maintaining
  per-thread held-sets and the global acquisition-order graph, raising
  ``LockCycleError`` on deadlock *potential*.  The runtime half of the
  CD11xx concurrency-discipline pass (``docs/static_analysis.md``).
* ``rescheck`` — the runtime resource-leak sanitizer
  (``MXNET_RESCHECK=1``): a tracked-handle registry over arena pages,
  sockets, futures, threads and temp files, reporting live handles at
  ``drain()``/``stop()``/atexit as ``ResourceLeakError`` with creation
  stacks.  The runtime half of the RL12xx lifecycle pass.
"""
from __future__ import annotations

from .faults import (FaultInjected, FaultPlan, LoopKilled, current,
                     install, maybe_inject, set_role, uninstall)
from .lockcheck import LockCycleError
from .rescheck import ResourceLeakError
from . import lockcheck
from . import rescheck

__all__ = [
    "FaultInjected", "FaultPlan", "LoopKilled", "current", "install",
    "maybe_inject", "set_role", "uninstall",
    "LockCycleError", "lockcheck",
    "ResourceLeakError", "rescheck",
]
