"""Deterministic test harnesses for the framework itself.

Import-light by design (stdlib only at import time): ``engine.py`` and
``parallel/dist_kvstore.py`` import :mod:`mxnet_tpu.testing.faults` on
their hot paths, so this package must never pull in jax/numpy.

* ``faults`` — seeded, replayable fault injection for the distributed
  tier and the engine (``FaultPlan``, ``MXNET_FAULT_PLAN``).  See
  ``docs/fault_tolerance.md``.
"""
from __future__ import annotations

from .faults import (FaultInjected, FaultPlan, LoopKilled, current,
                     install, maybe_inject, set_role, uninstall)

__all__ = [
    "FaultInjected", "FaultPlan", "LoopKilled", "current", "install",
    "maybe_inject", "set_role", "uninstall",
]
