"""Weight initializers.

Reference: ``python/mxnet/initializer.py`` — an ``Initializer`` registry
(``@register``, string/alias lookup) whose instances are callables writing
into pre-allocated arrays, with name-pattern dispatch (``_bias``→zero etc.)
via ``InitDesc``.

TPU-native: initializers *return* fresh device arrays (functional, XLA
buffers are immutable) drawn from the global threefry stream, instead of
mutating a buffer in place.  The registry, string-construction
(``mx.init.Xavier(magnitude=2)`` or ``"xavier"``) and name-pattern defaults
are preserved.
"""
from __future__ import annotations

import json
import math

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from . import random as _random

_INIT_REGISTRY = {}


def register(klass):
    """Register an initializer class under its lowercased name."""
    name = klass.__name__.lower()
    _INIT_REGISTRY[name] = klass
    return klass


class InitDesc(str):
    """Parameter name + attrs hint passed to initializers.

    Parity: ``python/mxnet/initializer.py`` InitDesc — lets one initializer
    dispatch on parameter naming conventions (``*_bias`` → zeros, ...).
    """

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer (parity: initializer.Initializer)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, shape, dtype=jnp.float32):
        """Produce the initial array for parameter ``desc`` of ``shape``."""
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init = desc.attrs.get("__init__", "")
        if init:
            return create(init)._init_impl(desc, shape, dtype)
        name = str(desc)
        if name.endswith("weight"):
            return self._init_weight(desc, shape, dtype)
        if name.endswith("bias"):
            return self._init_zero(desc, shape, dtype)
        if name.endswith("gamma"):
            return self._init_one(desc, shape, dtype)
        if name.endswith("beta"):
            return self._init_zero(desc, shape, dtype)
        if name.endswith("running_mean") or name.endswith("moving_mean"):
            return self._init_zero(desc, shape, dtype)
        if name.endswith("running_var") or name.endswith("moving_var"):
            return self._init_one(desc, shape, dtype)
        return self._init_weight(desc, shape, dtype)

    def _init_impl(self, desc, shape, dtype):
        return self._init_weight(desc, shape, dtype)

    def _init_weight(self, desc, shape, dtype):  # pragma: no cover - abstract
        raise NotImplementedError

    @staticmethod
    def _init_zero(desc, shape, dtype):
        return jnp.zeros(shape, dtype)

    @staticmethod
    def _init_one(desc, shape, dtype):
        return jnp.ones(shape, dtype)

    def __repr__(self):
        return "%s(%s)" % (self.__class__.__name__, self._kwargs)


def create(init, **kwargs):
    """Resolve a string / instance / json-dumps into an Initializer."""
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform()
    if isinstance(init, (list, tuple)) and len(init) == 2:
        # decoded dumps() form (symbol JSON attrs arrive pre-parsed)
        name, kw = init
        return _INIT_REGISTRY[str(name).lower()](**kw)
    if isinstance(init, str):
        s = init.strip()
        if s.startswith("["):  # dumps() round-trip
            name, kw = json.loads(s)
            return _INIT_REGISTRY[name](**kw)
        key = s.lower()
        if key not in _INIT_REGISTRY:
            raise MXNetError("unknown initializer %r" % init)
        return _INIT_REGISTRY[key](**kwargs)
    raise TypeError("cannot create initializer from %r" % (init,))


@register
class Zero(Initializer):
    def _init_weight(self, desc, shape, dtype):
        return jnp.zeros(shape, dtype)


_INIT_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, desc, shape, dtype):
        return jnp.ones(shape, dtype)


_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, shape, dtype):
        return jax.random.uniform(
            _random.next_key(), shape, jnp.float32, -self.scale, self.scale
        ).astype(dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, shape, dtype):
        return (self.sigma * jax.random.normal(
            _random.next_key(), shape, jnp.float32)).astype(dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, shape, dtype):
        nout = shape[0]
        nin = int(_np.prod(shape[1:])) if len(shape) > 1 else 1
        key = _random.next_key()
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(key, (nout, nin), jnp.float32, -1.0, 1.0)
        else:
            tmp = jax.random.normal(key, (nout, nin), jnp.float32)
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        return (self.scale * q.reshape(shape)).astype(dtype)


def _fan_in_out(shape, factor_type):
    hw_scale = 1.0
    if len(shape) < 2:
        raise MXNetError(
            "Xavier-family initializers need >=2-d shapes, got %s" % (shape,))
    if len(shape) > 2:
        hw_scale = float(_np.prod(shape[2:]))
    fan_in = shape[1] * hw_scale
    fan_out = shape[0] * hw_scale
    return fan_in, fan_out


@register
class Xavier(Initializer):
    """Parity: initializer.Xavier (rnd_type, factor_type, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, shape, dtype):
        fan_in, fan_out = _fan_in_out(shape, self.factor_type)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("invalid factor_type %r" % self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        key = _random.next_key()
        if self.rnd_type == "uniform":
            out = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        elif self.rnd_type == "gaussian":
            out = scale * jax.random.normal(key, shape, jnp.float32)
        else:
            raise MXNetError("invalid rnd_type %r" % self.rnd_type)
        return out.astype(dtype)


@register
class MSRAPrelu(Xavier):
    """Parity: initializer.MSRAPrelu."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernels (parity: initializer.Bilinear)."""

    def _init_weight(self, desc, shape, dtype):
        weight = _np.zeros(int(_np.prod(shape)), dtype=_np.float32)
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight.reshape(shape), dtype)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1, rest 0 (parity: initializer.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, shape, dtype):
        b = _np.zeros(shape, dtype=_np.float32)
        n = shape[0] // 4
        b[n:2 * n] = self.forget_bias  # gate order i, f, g, o
        return jnp.asarray(b, dtype)


@register
class FusedRNN(Initializer):
    """Initialize a packed RNN parameter blob by delegating to ``init``."""

    def __init__(self, init=None, state_size=0, num_layers=1, mode="lstm",
                 bidirectional=False, forget_bias=1.0):
        super().__init__()
        self._init = create(init) if init is not None else Uniform(0.1)
        self._forget = forget_bias

    def _init_weight(self, desc, shape, dtype):
        return self._init._init_weight(desc, shape, dtype)


class Load:
    """Initialize from a dict of arrays, falling back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            k.replace("arg:", "").replace("aux:", ""): v
            for k, v in param.items()
        }
        self.default_init = default_init

    def __call__(self, desc, shape, dtype=jnp.float32):
        name = str(desc)
        if name in self.param:
            arr = self.param[name]
            arr = arr.data() if hasattr(arr, "data") else jnp.asarray(arr)
            if tuple(arr.shape) != tuple(shape):
                raise MXNetError(
                    "Load: shape mismatch for %s: %s vs %s"
                    % (name, arr.shape, shape))
            return arr.astype(dtype)
        if self.default_init is None:
            raise MXNetError("Load: no init for %s" % name)
        return self.default_init(desc, shape, dtype)


class Mixed:
    """Pattern-dispatch initializer (parity: initializer.Mixed)."""

    def __init__(self, patterns, initializers):
        import re

        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must pair up")
        self.map = [(re.compile(p), init) for p, init in
                    zip(patterns, initializers)]

    def __call__(self, desc, shape, dtype=jnp.float32):
        for prog, init in self.map:
            if prog.match(str(desc)):
                return init(desc, shape, dtype)
        raise MXNetError("no matching pattern for %s" % str(desc))
