"""Global spatial-layout policy: channels-last on TPU, NCHW for parity.

The reference is NCHW-native end to end (``src/operator/nn/convolution.cc``
defaults, cuDNN's preferred layout).  TPUs are the opposite: XLA:TPU tiles
convolutions onto the MXU in channels-last (NHWC) form, and an NCHW graph
pays relayout copies around convs.  This module is the single switch that
decides which layout spatial layers and the model zoo pick when the user
does not say.

Two tiers, deliberately different:

- **Bare gluon layers** (``nn.Conv2D``/pooling/``nn.BatchNorm`` built with
  no ``layout=``/``axis=``) resolve through :func:`default_layout`.  Under
  the default ``"auto"`` policy this is ALWAYS channel-first — reference
  semantics — because a bare layer has no input-boundary adapter: user code
  feeding NCHW batches must keep working on every backend.  Channels-last
  for bare layers is opt-in via :class:`layout_scope` or an explicit
  ``layout=`` argument.
- **Model-zoo networks** (built on ``_LayoutNet``) resolve through
  :func:`preferred_layout`.  Under ``"auto"`` this picks channels-last iff
  the default backend is an accelerator; the nets keep NCHW input
  semantics by transposing once at the stem, so the switch is invisible
  to callers.  ``pretrained=True`` loaders pin ``"NCHW"`` — shipped
  checkpoints are reference-layout.

Policy values: ``"auto"`` (the default, see above), the
``"NCHW"``/``"channel_first"`` family, or the ``"NHWC"``/``"channel_last"``
family.  :func:`set_default_layout` sets the PROCESS-wide base policy;
:class:`layout_scope` applies a thread-local override inside a ``with``
block (like other scope state, it does not leak across threads).

Layout is resolved at **layer construction** time (it is a static property
of the compiled program; changing the policy later never re-lays-out live
parameters).  Conv weights are stored in the layout the layer was built
with (OIHW for NCHW graphs, HWIO for NHWC graphs): to move checkpoints
across machine kinds, pin an explicit layout.
"""
from __future__ import annotations

import threading

_CHANNEL_FIRST = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
_CHANNEL_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}
_VALID = ({"auto", "channel_first", "channel_last"}
          | set(_CHANNEL_FIRST.values()) | set(_CHANNEL_LAST.values()))

_process_policy = ["auto"]
_state = threading.local()
_auto_cache = [None]


def _auto_channel_last():
    """True iff compute lands on an accelerator (used by
    :func:`preferred_layout` only)."""
    if _auto_cache[0] is None:
        try:
            import jax

            _auto_cache[0] = jax.default_backend() not in ("cpu",)
        except Exception:
            _auto_cache[0] = False
    return _auto_cache[0]


def _canonical(policy):
    if policy in _CHANNEL_LAST.values() or policy == "channel_last":
        return "channel_last"
    if policy in _CHANNEL_FIRST.values() or policy == "channel_first":
        return "channel_first"
    return "auto"


def get_policy():
    """Active policy: thread-local scope override, else the process base."""
    return getattr(_state, "policy", None) or _process_policy[0]


def set_default_layout(policy):
    """Set the process-wide base layout policy; returns the previous one.

    Accepts ``"auto"``, ``"channel_first"``/``"NCHW"``-family names, or
    ``"channel_last"``/``"NHWC"``-family names.  Threads currently inside
    a :class:`layout_scope` keep their scoped override.
    """
    if policy not in _VALID:
        raise ValueError("unknown layout policy %r (want one of %s)"
                         % (policy, sorted(_VALID)))
    prev = _process_policy[0]
    _process_policy[0] = _canonical(policy)
    return prev


class layout_scope:
    """``with layout_scope("NHWC"): net = resnet50_v1()`` — thread-local
    scoped policy override."""

    def __init__(self, policy):
        if policy not in _VALID:
            raise ValueError("unknown layout policy %r (want one of %s)"
                             % (policy, sorted(_VALID)))
        self._policy = _canonical(policy)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_state, "policy", None)
        _state.policy = self._policy
        return self

    def __exit__(self, *exc):
        _state.policy = self._prev
        return False


def is_channel_last():
    """True iff BARE layers should build channels-last right now (explicit
    channel_last policy only — ``auto`` is channel-first for bare layers)."""
    return _canonical(get_policy()) == "channel_last"


def default_layout(ndim=2):
    """Layout a bare spatial layer picks when the caller does not say.

    ``auto`` → channel-first (reference semantics; safe for NCHW-feeding
    user code on every backend).  Explicit policies are honored.
    """
    table = _CHANNEL_LAST if is_channel_last() else _CHANNEL_FIRST
    return table[ndim]


def preferred_layout(ndim=2):
    """Layout a model-zoo net (with an NCHW-boundary stem adapter) picks.

    ``auto`` → channels-last iff the default backend is an accelerator;
    explicit policies are honored.
    """
    c = _canonical(get_policy())
    last = _auto_channel_last() if c == "auto" else (c == "channel_last")
    return (_CHANNEL_LAST if last else _CHANNEL_FIRST)[ndim]


def channel_axis(layout):
    """Channel axis index for a layout string (1 or -1)."""
    return 1 if layout.startswith("NC") else -1


def current_channel_axis():
    """Channel axis implied for bare layers by the active policy (for
    concat/split sites that are built once and baked into the graph)."""
    return -1 if is_channel_last() else 1
