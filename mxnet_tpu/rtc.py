"""``mx.rtc`` — user-supplied device kernels.

Reference: ``mx.rtc.CudaModule`` (``include/mxnet/rtc.h:39``,
``src/common/rtc.cc:49``) — NVRTC-compiled CUDA source launchable on
NDArrays.  TPU-native replacement: the kernel language is **Pallas**
(the TPU kernel DSL) instead of CUDA C; ``PallasModule`` wraps a Pallas
kernel function into an NDArray-callable with tape integration, running
interpreted on CPU for tests and compiled on TPU.
"""
from __future__ import annotations

import jax

from .base import MXNetError
from .ops import registry as _reg


class PallasKernel:
    """A launchable kernel (parity: CudaModule.get_kernel result)."""

    def __init__(self, kernel_fn, out_shape, in_specs=None, out_specs=None,
                 grid=None, name=None, interpret=None, **pallas_kwargs):
        self._kernel_fn = kernel_fn
        self._out_shape = out_shape
        self._name = name or getattr(kernel_fn, "__name__", "pallas_kernel")
        self._kwargs = dict(pallas_kwargs)
        if in_specs is not None:
            self._kwargs["in_specs"] = in_specs
        if out_specs is not None:
            self._kwargs["out_specs"] = out_specs
        if grid is not None:
            self._kwargs["grid"] = grid
        self._interpret = interpret

    def _interp(self):
        if self._interpret is not None:
            return self._interpret
        try:
            return jax.default_backend() not in ("tpu", "axon")
        except Exception:
            return True

    def launch(self, *arrays):
        """Run on NDArrays; differentiable if the kernel is (via jax.vjp
        over the pallas_call, which Pallas supports for simple kernels)."""
        from jax.experimental import pallas as pl

        def fn(*raw):
            out = pl.pallas_call(
                self._kernel_fn,
                out_shape=self._out_shape,
                interpret=self._interp(),
                **self._kwargs,
            )(*raw)
            return out if isinstance(out, tuple) else (out,)

        results = _reg.invoke_fn(fn, list(arrays), op_name=self._name)
        return results[0] if len(results) == 1 else results

    __call__ = launch


class PallasModule:
    """Named collection of Pallas kernels (parity: CudaModule)."""

    def __init__(self, **kernels):
        self._kernels = dict(kernels)

    def get_kernel(self, name, *args, **kwargs):
        k = self._kernels.get(name)
        if k is None:
            raise MXNetError("no kernel %r in module" % name)
        return k


class CudaModule:
    def __init__(self, *a, **kw):
        raise MXNetError(
            "CUDA RTC does not exist on TPU; write the kernel in Pallas "
            "and wrap it with mx.rtc.PallasKernel (same launch-on-NDArray "
            "contract)")
