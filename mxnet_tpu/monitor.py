"""Monitor — per-layer output/statistic taps during training.

Capability parity: ``python/mxnet/monitor.py`` (Monitor installed via
``Executor.set_monitor_callback``; ``tic/toc/toc_print`` batch protocol).
TPU-native note: outputs surface as NDArrays backed by device buffers; the
stat function runs host-side on asnumpy'd values at ``toc`` time so no
monitoring code ends up inside the compiled executable.
"""
from __future__ import annotations

import logging
import re

from .ndarray.ndarray import NDArray


class Monitor:
    """Parameters
    ----------
    interval : int — call stats every `interval` batches
    stat_func : fn(NDArray) -> NDArray, default mean(abs(x))
    pattern : regex selecting which names to monitor
    sort : sort output statistics by name
    """

    def __init__(self, interval, stat_func=None, pattern='.*', sort=False):
        if stat_func is None:
            def asum_stat(x):
                return float(abs(x).mean().asscalar()) \
                    if isinstance(x, NDArray) else float(x)

            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, array):
        """The callback wired into executors."""
        if not self.activated or not self.re_prog.match(str(name)):
            return
        self.queue.append((self.step, str(name), self.stat_func(array)))

    # alias used by install_monitor plumbing
    @property
    def tip(self):
        return self.stat_helper

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = sorted(self.queue) if self.sort else self.queue
        for n, k, v_list in queue:
            res.append((n, k, str(v_list)))
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            logging.info('Batch: %7d %30s %s', n, k, v)
