"""Quantized (int8) operator family.

Parity: ``src/operator/quantization/*.cc`` — quantize_v2, requantize, and
the ``_contrib_quantized_*`` compute ops the INT8 graph pass swaps in
(executed by MKL-DNN/cuDNN in the reference).

TPU-native: int8×int8 contractions run on the MXU with int32 accumulation
(``preferred_element_type=int32`` on ``dot_general``/``conv``) — the MXU's
native int8 mode — and elementwise/quantize steps stay in XLA.  Every
compute op follows the reference's calling convention: quantized tensor
inputs each carry trailing (min, max) range scalars, and outputs return
(out, min_out, max_out).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _ranges(min_r, max_r, dtype):
    """Symmetric-int8 / uint8 scale for a [min, max] float range."""
    if dtype == jnp.uint8:
        return 255.0 / jnp.maximum(max_r - min_r, 1e-12), 0.0
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return 127.0 / jnp.maximum(amax, 1e-12), 0.0


@register("_contrib_quantize_v2", num_outputs=3)
def _quantize_v2(data, out_type="int8", min_calib_range=None,
                 max_calib_range=None):
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.min(data)
        max_r = jnp.max(data)
    else:
        min_r = jnp.asarray(min_calib_range, jnp.float32)
        max_r = jnp.asarray(max_calib_range, jnp.float32)
    if out_type == "uint8":
        scale = 255.0 / jnp.maximum(max_r - min_r, 1e-12)
        q = jnp.clip(jnp.round((data - min_r) * scale), 0, 255)
        return q.astype(jnp.uint8), min_r.reshape(()), max_r.reshape(())
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    scale = 127.0 / jnp.maximum(amax, 1e-12)
    q = jnp.clip(jnp.round(data * scale), -127, 127)
    return q.astype(jnp.int8), (-amax).reshape(()), amax.reshape(())


@register("_contrib_requantize", num_outputs=3)
def _requantize(data, min_range, max_range, out_type="int8",
                min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 with a new calibrated range."""
    # float value represented by the int32 accumulator
    in_scale = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) \
        / (2.0 ** 31 - 1)
    f = data.astype(jnp.float32) * in_scale
    if min_calib_range is not None and max_calib_range is not None:
        amax = max(abs(float(min_calib_range)), abs(float(max_calib_range)))
        amax = jnp.asarray(amax, jnp.float32)
    else:
        amax = jnp.maximum(jnp.max(jnp.abs(f)), 1e-12)
    q = jnp.clip(jnp.round(f * (127.0 / amax)), -127, 127)
    return q.astype(jnp.int8), -amax, amax


def _dequant(q, min_r, max_r):
    if q.dtype == jnp.uint8:
        scale = (max_r - min_r) / 255.0
        return q.astype(jnp.float32) * scale + min_r
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return q.astype(jnp.float32) * (amax / 127.0)


def _int32_out_range(min_a, max_a, min_b, max_b):
    """Float range represented by the int32 accumulator of an int8×int8
    contraction (reference: quantization_utils.h
    GetQuantizedToQuantizedScale)."""
    sa = jnp.maximum(jnp.abs(min_a), jnp.abs(max_a)) / 127.0
    sb = jnp.maximum(jnp.abs(min_b), jnp.abs(max_b)) / 127.0
    out = sa * sb * (2.0 ** 31 - 1)
    return -out, out


@register("_contrib_quantized_fully_connected", num_outputs=3,
          inputs=("data", "weight", "bias", "min_data", "max_data",
                  "min_weight", "max_weight", "min_bias", "max_bias"))
def _quantized_fc(data, weight, bias=None, min_data=None, max_data=None,
                  min_weight=None, max_weight=None, min_bias=None,
                  max_bias=None, num_hidden=1, no_bias=False, flatten=True):
    x = data.reshape(data.shape[0], -1) if flatten else data
    acc = lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    lo, hi = _int32_out_range(min_data, max_data, min_weight, max_weight)
    if bias is not None and not no_bias:
        # rescale int8 bias into the int32 accumulator's scale
        sb = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        sacc = hi / (2.0 ** 31 - 1)
        acc = acc + jnp.round(bias.astype(jnp.float32) * sb
                              / sacc).astype(jnp.int32)
    return acc, lo, hi


@register("_contrib_quantized_conv", num_outputs=3,
          inputs=("data", "weight", "bias", "min_data", "max_data",
                  "min_weight", "max_weight", "min_bias", "max_bias"))
def _quantized_conv(data, weight, bias=None, min_data=None, max_data=None,
                    min_weight=None, max_weight=None, min_bias=None,
                    max_bias=None, kernel=(1, 1),
                    stride=(1, 1), dilate=(1, 1), pad=(0, 0), num_filter=1,
                    num_group=1, no_bias=False, layout="NCHW"):
    sh = tuple(int(s) for s in stride) if stride else (1, 1)
    dl = tuple(int(d) for d in dilate) if dilate else (1, 1)
    pd = tuple(int(p) for p in pad) if pad else (0, 0)
    acc = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8), sh,
        [(pd[0], pd[0]), (pd[1], pd[1])], rhs_dilation=dl,
        feature_group_count=int(num_group),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.int32)
    lo, hi = _int32_out_range(min_data, max_data, min_weight, max_weight)
    if bias is not None and not no_bias:
        sb = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        sacc = hi / (2.0 ** 31 - 1)
        acc = acc + jnp.round(bias.astype(jnp.float32) * sb
                              / sacc).astype(jnp.int32).reshape(1, -1, 1, 1)
    return acc, lo, hi


@register("_contrib_quantized_pooling", num_outputs=3)
def _quantized_pooling(data, min_data, max_data, kernel=(1, 1),
                       stride=(1, 1), pad=(0, 0), pool_type="max",
                       global_pool=False, pooling_convention="valid"):
    from .nn import _pooling

    # max/avg pooling commutes with the affine dequantization, so pool the
    # int values directly (avg in int32 then round back)
    x = data.astype(jnp.int32)
    out = _pooling(x.astype(jnp.float32), kernel=kernel, stride=stride,
                   pad=pad, pool_type=pool_type, global_pool=global_pool,
                   pooling_convention=pooling_convention)
    return jnp.round(out).astype(data.dtype), min_data, max_data


@register("_contrib_quantized_act", num_outputs=3)
def _quantized_act(data, min_data, max_data, act_type="relu"):
    if act_type != "relu":
        # PARITY, not a ceiling: the reference also supports only relu
        # ("_contrib_quantized_act only supports act_type=relu for now",
        # src/operator/quantization/quantized_activation.cc:54,110)
        raise NotImplementedError(
            "quantized act: only relu (same as the reference, "
            "quantized_activation.cc:110)")
    zero = jnp.zeros((), data.dtype)
    out = jnp.maximum(data, zero)
    return out, jnp.maximum(min_data, 0.0), max_data


@register("_contrib_quantized_flatten", num_outputs=3)
def _quantized_flatten(data, min_data, max_data):
    return data.reshape(data.shape[0], -1), min_data, max_data


@register("_contrib_quantized_elemwise_add", num_outputs=3)
def _quantized_elemwise_add(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    f = _dequant(lhs, lhs_min, lhs_max) + _dequant(rhs, rhs_min, rhs_max)
    amax = jnp.maximum(jnp.max(jnp.abs(f)), 1e-12)
    q = jnp.clip(jnp.round(f * (127.0 / amax)), -127, 127)
    return q.astype(jnp.int8), -amax, amax


@register("_contrib_quantized_elemwise_mul", num_outputs=3)
def _quantized_elemwise_mul(lhs, rhs, lhs_min, lhs_max, rhs_min, rhs_max):
    acc = lhs.astype(jnp.int32) * rhs.astype(jnp.int32)
    sa = jnp.maximum(jnp.abs(lhs_min), jnp.abs(lhs_max)) / 127.0
    sb = jnp.maximum(jnp.abs(rhs_min), jnp.abs(rhs_max)) / 127.0
    out = sa * sb * (2.0 ** 31 - 1)
    return acc, -out, out


@register("_contrib_quantized_concat", num_outputs=3)
def _quantized_concat(*arrays, num_args=1, dim=1):
    # input layout: [data_0..data_{n-1}, min_0..min_{n-1}, max_0..max_{n-1}]
    n = len(arrays) // 3
    qs = arrays[:n]
    mins = arrays[n:2 * n]
    maxs = arrays[2 * n:]
    # requantize every input to the widest range, then concat
    amax = mins[0] * 0.0
    for lo, hi in zip(mins, maxs):
        amax = jnp.maximum(amax, jnp.maximum(jnp.abs(lo), jnp.abs(hi)))
    outs = []
    for q, lo, hi in zip(qs, mins, maxs):
        f = _dequant(q, lo, hi)
        outs.append(jnp.clip(jnp.round(f * (127.0 / amax)),
                             -127, 127).astype(jnp.int8))
    return jnp.concatenate(outs, axis=int(dim)), -amax, amax


@register("_contrib_quantized_embedding", num_outputs=3)
def _quantized_embedding(data, weight, min_weight, max_weight,
                         input_dim=1, output_dim=1, dtype="float32"):
    out = jnp.take(weight, data.astype(jnp.int32), axis=0, mode="clip")
    return out, min_weight, max_weight


@register("_contrib_quantized_batch_norm", num_outputs=3,
          inputs=("data", "gamma", "beta", "moving_mean", "moving_var",
                  "min_data", "max_data"))
def _quantized_batch_norm(data, gamma, beta, moving_mean, moving_var,
                          min_data, max_data, eps=1e-3, momentum=0.9,
                          fix_gamma=True, use_global_stats=False,
                          axis=1, min_calib_range=None,
                          max_calib_range=None):
    f = _dequant(data, min_data, max_data)
    g = jnp.ones_like(moving_mean) if fix_gamma else gamma
    shape = [1] * f.ndim
    shape[axis] = -1
    out = ((f - moving_mean.reshape(shape))
           * (g / jnp.sqrt(moving_var + eps)).reshape(shape)
           + beta.reshape(shape))
    if min_calib_range is not None:
        amax = jnp.asarray(max(abs(float(min_calib_range)),
                               abs(float(max_calib_range))), jnp.float32)
    else:
        amax = jnp.maximum(jnp.max(jnp.abs(out)), 1e-12)
    q = jnp.clip(jnp.round(out * (127.0 / amax)), -127, 127)
    return q.astype(jnp.int8), -amax, amax


@register("_contrib_calibrate_entropy", num_outputs=2)
def _calibrate_entropy(hist, hist_edges, num_quantized_bins=255):
    """KL-entropy calibration threshold from a histogram (reference:
    quantization/calibrate.cc).  Returns (min, max) of the optimal range.

    The KL search over truncation thresholds is a host-side algorithm in
    the reference too; here it runs as a small XLA loop over candidate
    thresholds with fixed bin geometry."""
    nbins = hist.shape[0]
    centers = (hist_edges[:-1] + hist_edges[1:]) / 2.0
    amax = jnp.max(jnp.abs(hist_edges))
    nq = int(num_quantized_bins)
    # evaluate KL for a fixed grid of candidate thresholds
    n_cand = 64
    fracs = (jnp.arange(n_cand, dtype=jnp.float32) + 1.0) / n_cand

    def kl_for(frac):
        th = amax * frac
        w = jnp.abs(centers) <= th
        p = jnp.where(w, hist, 0.0)
        outliers = jnp.sum(jnp.where(w, 0.0, hist))
        # assign outliers to the edge bins like the reference
        p = p + outliers / jnp.maximum(jnp.sum(w.astype(jnp.float32)), 1.0)
        # quantize p into nq bins then expand back
        bin_idx = jnp.clip(((jnp.abs(centers) / jnp.maximum(th, 1e-12))
                            * (nq / 2)).astype(jnp.int32), 0, nq - 1)
        q_sums = jnp.zeros((nq,), jnp.float32).at[bin_idx].add(
            jnp.where(w, p, 0.0))
        q_cnts = jnp.zeros((nq,), jnp.float32).at[bin_idx].add(
            w.astype(jnp.float32))
        q = jnp.where(w, q_sums[bin_idx] / jnp.maximum(q_cnts[bin_idx], 1.0),
                      0.0)
        pn = p / jnp.maximum(jnp.sum(p), 1e-12)
        qn = q / jnp.maximum(jnp.sum(q), 1e-12)
        return jnp.sum(jnp.where((pn > 0) & (qn > 0),
                                 pn * jnp.log(pn / jnp.maximum(qn, 1e-12)),
                                 0.0))

    kls = jax.vmap(kl_for)(fracs)
    best = fracs[jnp.argmin(kls)] * amax
    return -best, best
