"""Pallas TPU kernels: fused flash attention.

The reference's fused-attention story is two CUDA kernels
(``_contrib_interleaved_matmul_selfatt_qk``/``_valatt``,
``src/operator/contrib/transformer.cc:650-780``) that still materialize
the (T, T) score matrix.  TPU-native replacement: one Pallas kernel doing
blocked online-softmax attention (flash attention) — scores never leave
VMEM, HBM traffic is O(T·D) instead of O(T²), and the MXU sees back-to-
back (block_q × D)·(D × block_k) matmuls.

On non-TPU backends the kernel runs through the Pallas interpreter
(tests), or falls back to a plain jnp attention when shapes don't tile.
Backward: the forward saves only (q, k, v) — O(T·D) residuals — and the
backward RECOMPUTES attention in plain XLA, which materializes the (T, T)
score matrix transiently.  The forward memory win (inference, frozen
backbones, activation checkpointing boundaries) is real; a fully blocked
backward kernel is future work, so very long TRAINING sequences should
use ring attention (parallel/ring_attention.py) to shard T first.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _flash_dispatch(q, k, v, scale, causal, block_q, block_k):
    """Pick compiled vs interpreted pallas at LOWERING time.

    ``jax.lax.platform_dependent`` resolves per lowering platform, so the
    same traced computation runs the real kernel on TPU and the
    interpreter on the host — regardless of where the surrounding jit or
    eager dispatch ends up placed (a cpu-committed input must never see
    the compiled TPU kernel).
    """
    import functools as _ft

    run = _ft.partial(_flash_pallas, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k)
    # compiled kernel ONLY on tpu; every other platform (cpu, and
    # untested cuda/rocm) goes through the interpreter
    return jax.lax.platform_dependent(
        q, k, v,
        tpu=_ft.partial(run, interpret=False),
        default=_ft.partial(run, interpret=True))


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q,
                      block_k, scale, causal):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale           # (bq, D)
    t_kv = k_ref.shape[1]
    n_k = t_kv // block_k
    qi = pl.program_id(1)
    row = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(i * block_k, block_k), :] \
            .astype(jnp.float32)                        # (bk, D)
        v = v_ref[0, pl.dslice(i * block_k, block_k), :] \
            .astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        if causal:
            col = i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    safe_l = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)


def _flash_pallas(q, k, v, scale, causal, block_q, block_k,
                  interpret=False):
    from jax.experimental import pallas as pl

    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(bh, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
        interpret=interpret,
    )(q, k, v)
    return out


def _attention_ref(q, k, v, scale, causal):
    """Plain jnp attention (fallback + backward recompute)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_kv = s.shape[-2], s.shape[-1]
        row = jnp.arange(t_q)[:, None]
        col = jnp.arange(t_kv)[None, :]
        s = jnp.where(col <= row, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, scale, causal, block_q, block_k):
    return _flash_dispatch(q, k, v, scale, causal, block_q, block_k)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    out = _flash_dispatch(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_:
                     _attention_ref(q_, k_, v_, scale, causal), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _tiles(t, preferred):
    for b in (preferred, 128, 64, 32, 16, 8):
        if b <= t and t % b == 0:
            return b
    return None


@register("_contrib_flash_attention", inputs=("query", "key", "value"))
def flash_attention(query, key, value, scale=None, causal=False,
                    block_q=128, block_k=128):
    """Fused multi-head attention, one Pallas kernel per (batch·head).

    Inputs (B, H, T, D) [or (BH, T, D)]; returns same shape.  Scores are
    computed blockwise with an online softmax; ``scale`` defaults to
    1/sqrt(D).  Falls back to plain XLA attention when T doesn't tile.
    """
    squeeze = query.ndim == 3
    if squeeze:
        query, key, value = (x[:, None] if x.ndim == 3 else x
                             for x in (query, key, value))
    b, h, t_q, d = query.shape
    t_kv = key.shape[2]
    if scale is None or scale == 0:
        scale = 1.0 / (d ** 0.5)
    q3 = query.reshape(b * h, t_q, d)
    k3 = key.reshape(b * h, t_kv, d)
    v3 = value.reshape(b * h, t_kv, d)
    bq = _tiles(t_q, int(block_q))
    bk = _tiles(t_kv, int(block_k))
    if bq is None or bk is None:
        out3 = _attention_ref(q3, k3, v3, scale, causal)
    else:
        out3 = _flash_attention(q3, k3, v3, float(scale), bool(causal),
                                bq, bk)
    out = out3.reshape(b, h, t_q, d)
    return out[:, 0] if squeeze else out
