"""Pallas TPU kernels: fused flash attention (forward AND backward).

The reference's fused-attention story is two CUDA kernels
(``_contrib_interleaved_matmul_selfatt_qk``/``_valatt``,
``src/operator/contrib/transformer.cc:650-780``) that still materialize
the (T, T) score matrix.  TPU-native replacement: Pallas kernels doing
blocked online-softmax attention (flash attention) — scores never leave
VMEM, HBM traffic is O(T·D) instead of O(T²), and the MXU sees back-to-
back (block_q × D)·(D × block_k) matmuls.

Backward is the standard two-pass flash backward (Dao et al.):
the forward saves (q, k, v, o, lse) — O(T·D) residuals — then one kernel
recomputes p blockwise to accumulate dq over k-blocks, and a second
accumulates dk/dv over q-blocks.  No (T, T) buffer exists in either
direction, so long-context TRAINING runs at O(T·D) memory; ring attention
(parallel/ring_attention.py) composes on top to shard T across chips.

On non-TPU backends the kernels run through the Pallas interpreter
(tests), or fall back to plain jnp attention when shapes don't tile.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_EAGER_JIT_CACHE = {}

# minor-dim width for per-row scalars (lse, delta): TPU Mosaic tiles
# require the minor block dim to be a multiple of 128, so row scalars
# ride lane-broadcast as (..., t, 128) exactly like jax's own TPU flash
# kernels' l/m buffers
_LANES = 128


def _platform_pick(run, *args):
    """Compiled kernel ONLY on tpu; every other platform (cpu, and
    untested cuda/rocm) goes through the interpreter.

    The platform is resolved from the backend at TRACE time, NOT via
    ``jax.lax.platform_dependent``: on this jax version the cond over
    the platform index still LOWERS every branch, and the compiled-
    pallas branch refuses to lower for cpu — so a traced
    ``platform_dependent`` poisons every CPU jit that touches the op
    (the same bug ``ops/paged_attention.py`` works around, and the
    exact failure ``tests/test_forward[_contrib_flash_attention]``
    used to hit).  ``jax.default_backend()`` is a host-side query,
    safe under trace; committed-device placement off the default
    backend is not a supported mix for these kernels.
    """
    from jax import core as _core

    interpret = jax.default_backend() != "tpu"
    if not any(isinstance(a, _core.Tracer) for a in args):
        for a in args:
            devs = getattr(a, "devices", None)
            if callable(devs):
                ds = list(devs())
                if ds:
                    interpret = ds[0].platform != "tpu"
                    break
        # jit the eager call (cached per kernel+attrs): un-jitted
        # interpret-mode pallas dispatches one tiny executable per inner
        # op per grid point — minutes instead of milliseconds
        key = (run.func, tuple(sorted(run.keywords.items())), interpret)
        fn = _EAGER_JIT_CACHE.get(key)
        if fn is None:
            fn = jax.jit(functools.partial(run, interpret=interpret))
            _EAGER_JIT_CACHE[key] = fn
        return fn(*args)
    return run(*args, interpret=interpret)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q,
                      block_k, scale, causal):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale           # (bq, D)
    t_kv = k_ref.shape[1]
    n_k = t_kv // block_k
    qi = pl.program_id(1)
    row = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(i * block_k, block_k), :] \
            .astype(jnp.float32)                        # (bk, D)
        v = v_ref[0, pl.dslice(i * block_k, block_k), :] \
            .astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        if causal:
            col = i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    safe_l = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)
    # logsumexp per row; -inf rows (fully masked) stored as -inf.  The
    # row scalar is broadcast across a 128-lane minor dimension — TPU
    # Mosaic requires block minor dims divisible by 128 (or full), so a
    # bare (block_q,) output cannot tile; jax's own TPU flash kernels
    # store l/m the same way (flash_attention.py MIN_BLOCK_SIZE).
    lse = jnp.where(l[:, 0] == 0, -jnp.inf, m[:, 0] + jnp.log(safe_l[:, 0]))
    lse_ref[0] = lax.broadcast_in_dim(lse, (block_q, _LANES), (0,))


def _flash_pallas(q, k, v, scale, causal, block_q, block_k,
                  interpret=False):
    from jax.experimental import pallas as pl

    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward: dq kernel (parallel over q blocks) + dkv kernel (over k blocks)
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q, block_k, scale, causal):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32)                    # (bq, D)
    do = do_ref[0].astype(jnp.float32)                  # (bq, D)
    lse = lse_ref[0][:, :1]                             # (bq, 1) lane 0
    delta = delta_ref[0][:, :1]                         # (bq, 1) lane 0
    t_kv = k_ref.shape[1]
    n_k = t_kv // block_k
    qi = pl.program_id(1)
    row = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(i, dq):
        k = k_ref[0, pl.dslice(i * block_k, block_k), :] \
            .astype(jnp.float32)
        v = v_ref[0, pl.dslice(i * block_k, block_k), :] \
            .astype(jnp.float32)
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            col = i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(col <= row, s, -jnp.inf)
        # p is the NORMALIZED probability (lse folds in the row sum);
        # fully-masked rows have lse=-inf -> exp(-inf - -inf) guarded to 0
        p = jnp.where(jnp.isfinite(lse), jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = lax.fori_loop(0, n_k, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q, block_k, scale,
                          causal):
    from jax.experimental import pallas as pl

    k = k_ref[0].astype(jnp.float32)                    # (bk, D)
    v = v_ref[0].astype(jnp.float32)                    # (bk, D)
    t_q = q_ref.shape[1]
    n_q = t_q // block_q
    ki = pl.program_id(1)
    col = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * block_q, block_q), :] \
            .astype(jnp.float32)
        do = do_ref[0, pl.dslice(i * block_q, block_q), :] \
            .astype(jnp.float32)
        lse = lse_ref[0, pl.dslice(i * block_q, block_q), :1]
        delta = delta_ref[0, pl.dslice(i * block_q, block_q), :1]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        if causal:
            row = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(col <= row, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(lse), jnp.exp(s - lse), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        ds = p * (dp - delta)
        dk_new = dk + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, D)
        return dk_new, dv_new

    z = jnp.zeros((k.shape[0], k.shape[1]), jnp.float32)
    dk, dv = lax.fori_loop(0, n_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, do, lse, delta, scale, causal, block_q,
                      block_k, interpret=False):
    from jax.experimental import pallas as pl

    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, scale=scale, causal=causal),
        grid=(bh, t_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_kv, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, scale=scale, causal=causal),
        grid=(bh, t_kv // block_k),
        in_specs=[
            pl.BlockSpec((1, t_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, t_q, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, t_q, _LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, t_q, _LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_kv, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_kv, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _attention_ref(q, k, v, scale, causal):
    """Plain jnp attention (fallback for non-tiling shapes)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_kv = s.shape[-2], s.shape[-1]
        row = jnp.arange(t_q)[:, None]
        col = jnp.arange(t_kv)[None, :]
        s = jnp.where(col <= row, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, scale, causal, block_q, block_k):
    run = functools.partial(_flash_pallas, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
    out, _ = _platform_pick(run, q, k, v)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    run = functools.partial(_flash_pallas, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
    out, lse = _platform_pick(run, q, k, v)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    # delta_i = sum_d dO_id * O_id  (rowwise), O(T*D) — the only
    # off-kernel piece of the two-pass flash backward.  Broadcast across
    # the 128-lane minor dim to match the lse residual's tiled layout.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))
    run = functools.partial(_flash_bwd_pallas, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k)
    dq, dk, dv = _platform_pick(run, q, k, v, g, lse, delta)
    return dq, dk, dv


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def _tiles(t, preferred):
    """Largest workable block: divides ``t`` AND satisfies the Mosaic
    sublane rule (multiple of 8, or the full axis).  A user-preferred
    block that divides t but breaks the sublane rule is skipped in
    favor of the next conforming candidate rather than forcing the
    O(T^2) reference fallback."""
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= t and t % b == 0 and (b == t or b % 8 == 0):
            return b
    return None


@register("_contrib_flash_attention", inputs=("query", "key", "value"))
def flash_attention(query, key, value, scale=None, causal=False,
                    block_q=None, block_k=None):
    """Fused multi-head attention, one Pallas kernel per (batch·head).

    Inputs (B, H, T, D) [or (BH, T, D)]; returns same shape.  Scores are
    computed blockwise with an online softmax; ``scale`` defaults to
    1/sqrt(D).  Falls back to plain XLA attention when T doesn't tile.
    Differentiable end-to-end via the blocked flash backward (no (T, T)
    buffer in forward or backward).

    ``block_q``/``block_k`` default to ``min(T, 512)`` — tuned on v5e
    (tools/llama_ceiling.py block sweep: 512/512 runs the seq-512 llama
    bench 1.5x faster than 128/128; the VMEM footprint per block at
    d<=128 stays under ~1MB so large blocks are safe), while 1024+
    regresses (VMEM pressure starts serializing the pipeline).
    """
    squeeze = query.ndim == 3
    if squeeze:
        query, key, value = (x[:, None] if x.ndim == 3 else x
                             for x in (query, key, value))
    b, h, t_q, d = query.shape
    t_kv = key.shape[2]
    if scale is None or scale == 0:
        scale = 1.0 / (d ** 0.5)
    q3 = query.reshape(b * h, t_q, d)
    k3 = key.reshape(b * h, t_kv, d)
    v3 = value.reshape(b * h, t_kv, d)
    # short sequences: XLA's fused attention beats the kernel (v5e A/B:
    # BERT seq-128 994 vs 825 samples/s) and the (T,T) buffer is small;
    # the Pallas path earns its keep from T>=512 (llama seq-512: 132k vs
    # 112k tok/s).  Explicit block sizes force the kernel (tests, tuning).
    if block_q is None and block_k is None and t_q < 512 and t_kv < 512:
        return _finish(_attention_ref(q3, k3, v3, scale, causal),
                       b, h, t_q, d, squeeze)
    bq = _tiles(t_q, int(block_q) if block_q else min(t_q, 512))
    bk = _tiles(t_kv, int(block_k) if block_k else min(t_kv, 512))
    if bq is None or bk is None:
        out3 = _attention_ref(q3, k3, v3, scale, causal)
    else:
        out3 = _flash_attention(q3, k3, v3, float(scale), bool(causal),
                                bq, bk)
    return _finish(out3, b, h, t_q, d, squeeze)


def _finish(out3, b, h, t_q, d, squeeze):
    out = out3.reshape(b, h, t_q, d)
    return out[:, 0] if squeeze else out
