"""Neural-net operators on XLA.

Reference: ``src/operator/nn/`` (conv/FC/pool/norm/softmax/dropout, cuDNN and
MKL-DNN backed) and ``src/operator/rnn.cc`` (monolithic RNN op).  TPU-native:
convolutions are ``lax.conv_general_dilated`` (MXU-tiled by XLA), pooling is
``lax.reduce_window``, norms are fused elementwise trees XLA folds into
neighbouring matmuls, RNN is a ``lax.scan`` so the whole unrolled sequence
compiles to a single executable with static shapes.

Layout: MXNet's native layout is NCHW.  Every spatial op takes a ``layout``
attr and also accepts NHWC — the layout XLA/TPU prefers.  Which one gluon
layers pick when the caller does not say is decided by the policy in
``mxnet_tpu/layout.py``: bare layers stay channel-first (reference
semantics) unless an explicit policy/scope says otherwise, while model-zoo
networks auto-select channels-last on accelerators and keep accepting NCHW
input via one stem transpose.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ----------------------------------------------------------------------------
# FullyConnected
# ----------------------------------------------------------------------------


@register("FullyConnected", aliases=("fully_connected",),
          inputs=("data", "weight", "bias"))
def _fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                     flatten=True):
    """Parity: src/operator/nn/fully_connected.cc:258 (y = x·Wᵀ + b).

    Weight layout matches reference: (num_hidden, in_units); compute stays in
    the input dtype (bf16 in, bf16 out) with MXU accumulation in fp32.
    """
    x = data.reshape(data.shape[0], -1) if flatten else data
    y = lax.dot_general(
        x, weight,
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    if not no_bias and bias is not None:
        y = y + bias
    return y


# ----------------------------------------------------------------------------
# Convolution / Deconvolution
# ----------------------------------------------------------------------------

_CONV_DIMNUMS = {
    # layout -> (lhs_spec, rhs_spec, out_spec) for lax.conv_general_dilated
    "NCHW": ("NCHW", "OIHW", "NCHW"),
    "NHWC": ("NHWC", "HWIO", "NHWC"),
    "NCW": ("NCH", "OIH", "NCH"),
    "NWC": ("NHC", "HIO", "NHC"),
    "NCDHW": ("NCDHW", "OIDHW", "NCDHW"),
    "NDHWC": ("NDHWC", "DHWIO", "NDHWC"),
}


def _as_tuple(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


@register("Convolution", aliases=("convolution",),
          inputs=("data", "weight", "bias"))
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                 num_filter=0, num_group=1, no_bias=False, layout="NCHW",
                 cudnn_tune=None, cudnn_off=False, workspace=1024):
    """Parity: src/operator/nn/convolution.cc. XLA lowers straight to the MXU.

    ``weight`` is stored in the layout the dimension-numbers expect:
    OIHW for NCHW graphs, HWIO for NHWC graphs (TPU-preferred).
    """
    nd = len(kernel) if kernel else 2
    stride = _as_tuple(stride, nd) if stride else (1,) * nd
    dilate = _as_tuple(dilate, nd) if dilate else (1,) * nd
    pad = _as_tuple(pad, nd) if pad else (0,) * nd
    specs = _CONV_DIMNUMS[layout]
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, specs)
    # No preferred_element_type for sub-f32 inputs: the MXU already
    # accumulates bf16 products in f32 internally, and jax's conv transpose
    # rule cannot differentiate a widened-accumulation conv (cotangent f32
    # vs operand bf16 → dtype mismatch in the backward conv).
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        if layout.endswith("C") or layout in ("NWC", "NHWC", "NDHWC"):
            out = out + bias
        else:
            out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", aliases=("deconvolution",),
          inputs=("data", "weight", "bias"))
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(), pad=(),
                   adj=(), target_shape=(), num_filter=0, num_group=1, no_bias=True,
                   layout="NCHW", cudnn_tune=None, cudnn_off=False, workspace=1024):
    """Transposed conv (parity: src/operator/nn/deconvolution.cc).

    out = (in-1)*stride - 2*pad + dilate*(kernel-1) + 1 + adj per spatial
    dim (deconvolution-inl.h InferShape).  Lowered as the true transpose:
    ``conv_general_dilated`` with lhs_dilation=stride, a spatially-flipped
    kernel, and edge padding ``dilate*(k-1) - pad`` — the gradient of the
    matching Convolution, so XLA fuses it onto the MXU like any conv.
    """
    if layout not in ("NCHW", "NCW", "NCDHW"):
        raise ValueError("Deconvolution: channel-first layouts only")
    nd = len(kernel) if kernel else 2
    stride = _as_tuple(stride, nd) if stride else (1,) * nd
    pad = _as_tuple(pad, nd) if pad else (0,) * nd
    dilate = _as_tuple(dilate, nd) if dilate else (1,) * nd
    adj = _as_tuple(adj, nd) if adj else (0,) * nd
    if target_shape:
        # reference solves pad from the requested output size, absorbing
        # an odd remainder into adj (deconvolution-inl.h InferPad:
        # pad = (total+1)/2, adj = total % 2)
        target = _as_tuple(target_shape, nd)
        total = [dilate[i] * (kernel[i] - 1) + stride[i]
                 * (data.shape[2 + i] - 1) + 1 - target[i]
                 for i in range(nd)]
        pad = tuple((t + 1) // 2 for t in total)
        adj = tuple(t % 2 for t in total)
    g = num_group
    c_in = weight.shape[0]
    # weight layout (C_in, C_out/g, *k) → flip spatial, regroup to
    # (C_out, C_in/g, *k) for OIHW dimension numbers
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    w = w.reshape((g, c_in // g) + w.shape[1:])
    w = jnp.swapaxes(w, 1, 2)
    w = w.reshape((g * w.shape[1], c_in // g) + tuple(kernel))
    specs = _CONV_DIMNUMS[layout]
    dn = lax.conv_dimension_numbers(data.shape, w.shape, specs)
    pads = [(dilate[i] * (kernel[i] - 1) - pad[i],
             dilate[i] * (kernel[i] - 1) - pad[i] + adj[i])
            for i in range(nd)]
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=g,
    ).astype(data.dtype)
    if not no_bias and bias is not None:
        if layout in ("NWC", "NHWC", "NDHWC"):
            out = out + bias
        else:
            out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ----------------------------------------------------------------------------
# Pooling
# ----------------------------------------------------------------------------


@register("Pooling", aliases=("pooling",))
def _pooling(data, kernel=(), pool_type="max", stride=(), pad=(), global_pool=False,
             pooling_convention="valid", layout="NCHW", count_include_pad=True,
             cudnn_off=False):
    """Parity: src/operator/nn/pooling.cc via lax.reduce_window."""
    if layout in ("NCHW", "NCW", "NCDHW"):
        spatial = tuple(range(2, data.ndim))
    else:
        spatial = tuple(range(1, data.ndim - 1))
    if global_pool:
        if pool_type == "max":
            return jnp.max(data, axis=spatial, keepdims=True)
        return jnp.mean(data, axis=spatial, keepdims=True)
    nd = len(kernel)
    stride = _as_tuple(stride, nd) if stride else (1,) * nd
    pad = _as_tuple(pad, nd) if pad else (0,) * nd
    window = [1] * data.ndim
    strides = [1] * data.ndim
    pads = [(0, 0)] * data.ndim
    for i, ax in enumerate(spatial):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        lo = pad[i]
        hi = pad[i]
        if pooling_convention == "full":
            # ceil-mode: add extra high padding so the last window fits
            size = data.shape[ax] + 2 * pad[i] - kernel[i]
            rem = size % stride[i]
            if rem:
                hi += stride[i] - rem
        pads[ax] = (lo, hi)
    # NOTE: init values must be Python scalars — an array init stops jax
    # from lowering to the reduce_window_max/add primitives that carry the
    # autodiff rules ("Linearization failed..." under vjp-of-jit).
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        return lax.reduce_window(data, init, lax.max,
                                 window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0., lax.add,
                                   window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1
            for i in range(nd):
                denom *= kernel[i]
            return summed / jnp.asarray(denom, data.dtype)
        ones = jnp.ones(data.shape, data.dtype)
        counts = lax.reduce_window(ones, 0., lax.add,
                                   window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        p2 = lax.reduce_window(jnp.abs(data) ** 2, 0.,
                               lax.add, window, strides, pads)
        return jnp.sqrt(p2)
    raise ValueError("unknown pool_type %r" % pool_type)


# ----------------------------------------------------------------------------
# Normalization
# ----------------------------------------------------------------------------


@register("BatchNorm", aliases=("batch_norm",), needs_mode=True, num_outputs=3)
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3, momentum=0.9,
                fix_gamma=True, use_global_stats=False, output_mean_var=False,
                axis=1, cudnn_off=False, _mode="predict"):
    """Parity: src/operator/nn/batch_norm.cc.

    Returns (out, new_moving_mean, new_moving_var); the imperative/gluon layer
    writes the aux outputs back into its running-stat arrays (the reference
    mutates aux states in place inside the op — impossible on immutable XLA
    buffers, so state threading is explicit).
    """
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    reduce_axes = tuple(i for i in range(data.ndim) if i != axis % data.ndim)
    bshape = [1] * data.ndim
    bshape[axis % data.ndim] = data.shape[axis % data.ndim]
    if _mode == "train" and not use_global_stats:
        # One-pass statistics: sum and sum-of-squares are SIBLING
        # reduces over one input, which XLA multi-output fusion computes
        # in a single HBM pass — jnp.var's mean-then-centered-moments
        # form is two dependent passes and re-reads the whole activation
        # (measured: BN-stat reductions were ~34% of the ResNet-50 train
        # step; this moves the chip ceiling ~6%).  The bare E[x²]-E[x]²
        # identity catastrophically cancels when |mean| >> std, so shift
        # by the RUNNING mean first: var = E[(x-c)²] - (E[x]-c)² is
        # exact for any c, and with c tracking the true mean the
        # subtracted term stays ~0 — exactly the failure mode removed.
        c = lax.stop_gradient(moving_mean.astype(jnp.float32))
        xc = data.astype(jnp.float32) - c.reshape(bshape)
        d1 = jnp.mean(xc, axis=reduce_axes)
        d2 = jnp.mean(xc * xc, axis=reduce_axes)
        mean = c + d1
        # Conditioning floor: the shifted identity loses ~(d2/var)
        # ulps, so variance below d2·2⁻²⁰ is not resolvable in f32 —
        # flooring there keeps rsqrt bounded instead of exploding on
        # rounding noise.  In the one regime that hits the floor (a
        # FRESH running mean on data with |mean|/std > ~2¹⁰, e.g. a
        # constant-offset feature before any stat update), the output
        # is conservatively under-scaled while the running mean
        # converges — geometric at the momentum rate (0.9 per update:
        # ~44 updates until a 2¹⁰ shift ratio drops below the floor
        # threshold, ~100+ for full exactness).  Alternatives were
        # measured and rejected: a lax.cond exact-recompute fallback
        # reproducibly crashes the remote TPU compile service on the
        # full train step, and a subsample-mean shift breaks XLA's
        # reduce fusion (2360 -> 2131 img/s).
        var = jnp.maximum(d2 - d1 * d1, d2 * (2.0 ** -20))
        new_mm = moving_mean * momentum + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_mv = moving_var * momentum + var.astype(moving_var.dtype) * (1 - momentum)
    else:
        mean, var = moving_mean.astype(jnp.float32), moving_var.astype(jnp.float32)
        new_mm, new_mv = moving_mean, moving_var
    # per-channel scale/shift stay f32; the big elementwise apply runs in
    # the INPUT dtype (bf16 on TPU) — upcasting the whole activation
    # tensor to f32 would double HBM traffic through BN fwd AND bwd
    inv = lax.rsqrt(var + eps)
    scale = (g.astype(jnp.float32) * inv).reshape(bshape).astype(data.dtype)
    shift = (beta.astype(jnp.float32)
             - mean * g.astype(jnp.float32) * inv).reshape(bshape) \
        .astype(data.dtype)
    out = data * scale + shift
    return out, lax.stop_gradient(new_mm), lax.stop_gradient(new_mv)


@register("LayerNorm", aliases=("layer_norm",))
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Parity: src/operator/nn/layer_norm.cc. Stats in fp32 for bf16 inputs."""
    x = data.astype(jnp.float32)
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    shape = [1] * data.ndim
    shape[axis % data.ndim] = data.shape[axis % data.ndim]
    out = (x - mean) * lax.rsqrt(var + eps)
    out = out * gamma.astype(jnp.float32).reshape(shape) + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)


@register("InstanceNorm", aliases=("instance_norm",))
def _instance_norm(data, gamma, beta, eps=1e-3):
    x = data.astype(jnp.float32)
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    out = (x - mean) * lax.rsqrt(var + eps)
    out = out * gamma.astype(jnp.float32).reshape(shape) + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)


@register("GroupNorm", aliases=("group_norm",))
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    x = data.astype(jnp.float32).reshape((n, num_groups, c // num_groups) + data.shape[2:])
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    out = x * gamma.astype(jnp.float32).reshape(shape) + beta.astype(jnp.float32).reshape(shape)
    return out.astype(data.dtype)


@register("L2Normalization", aliases=("l2_normalization",))
def _l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@register("RMSNorm", aliases=("rms_norm",))
def _rms_norm(data, gamma, axis=-1, eps=1e-6):
    """TPU-era addition (no reference counterpart; used by Llama-family models)."""
    x = data.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=axis, keepdims=True)
    out = x * lax.rsqrt(ms + eps) * gamma.astype(jnp.float32)
    return out.astype(data.dtype)


# ----------------------------------------------------------------------------
# Activations / softmax
# ----------------------------------------------------------------------------


@register("Activation", aliases=("activation",))
def _activation(data, act_type="relu"):
    fns = {
        "relu": jax.nn.relu,
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "softrelu": jax.nn.softplus,
        "softsign": jax.nn.soft_sign,
        "gelu": jax.nn.gelu,
        "silu": jax.nn.silu,
        "erf": jax.scipy.special.erf,
    }
    return fns[act_type](data)


@register("LeakyReLU", aliases=("leaky_relu",), needs_rng=True, needs_mode=True,
          inputs=("data", "gamma"))
def _leaky_relu(key, data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, _mode="predict"):
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 2 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * (jnp.exp(data) - 1.0))
    if act_type == "selu":
        a, s = 1.6732632423543772, 1.0507009873554805
        return s * jnp.where(data > 0, data, a * (jnp.exp(data) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        if _mode == "train":
            s = jax.random.uniform(key, data.shape, jnp.float32, lower_bound, upper_bound)
            return jnp.where(data > 0, data, s.astype(data.dtype) * data)
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise ValueError("unknown act_type %r" % act_type)


@register("softmax", inputs=("data", "length"))
def _softmax(data, axis=-1, temperature=None, length=None, use_length=False,
             dtype=None):
    x = data if temperature in (None, 1.0) else data / temperature
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        mask = steps.reshape(shape) < length.reshape(
            [x.shape[0]] + [1] * (x.ndim - 1)
        )
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if dtype is not None:
        out = out.astype(jnp.dtype(dtype))
    return out


@register("log_softmax")
def _log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data if temperature in (None, 1.0) else data / temperature
    out = jax.nn.log_softmax(x, axis=axis)
    if dtype is not None:
        out = out.astype(jnp.dtype(dtype))
    return out


@register("softmin")
def _softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("SoftmaxActivation")
def _softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_output_impl(data, label, grad_scale, ignore_label, use_ignore,
                         normalization):
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        normalization):
    prob = jax.nn.softmax(data, axis=-1)
    return prob, (prob, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, normalization,
                        res, g):
    # Reference semantics (src/operator/softmax_output-inl.h): backward IGNORES
    # the incoming head gradient and emits (p - onehot) * grad_scale.
    prob, label = res
    onehot = jax.nn.one_hot(label.astype(jnp.int32), prob.shape[-1], dtype=prob.dtype)
    scale = grad_scale
    if normalization == "batch":
        scale = scale / prob.shape[0]
    elif normalization == "valid" and use_ignore:
        valid = jnp.sum((label != ignore_label).astype(prob.dtype))
        scale = scale / jnp.maximum(valid, 1.0)
    grad = (prob - onehot) * scale
    if use_ignore:
        keep = (label != ignore_label).astype(prob.dtype)[..., None]
        grad = grad * keep
    return grad.astype(prob.dtype), jnp.zeros_like(label)


_softmax_output_impl.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", aliases=("softmax_output",))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    """Legacy fused softmax+CE head (parity: src/operator/softmax_output.cc)."""
    return _softmax_output_impl(data, label, grad_scale, ignore_label,
                                bool(use_ignore), normalization)


# ----------------------------------------------------------------------------
# Dropout / Embedding
# ----------------------------------------------------------------------------


@register("Dropout", aliases=("dropout",), needs_rng=True, needs_mode=True)
def _dropout(key, data, p=0.5, mode="training", axes=(), cudnn_off=False,
             _mode="predict"):
    if _mode != "train" and mode != "always":
        return data
    if p <= 0.0:
        return data
    shape = list(data.shape)
    for ax in axes or ():
        shape[ax] = 1
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    return jnp.where(keep, data / (1.0 - p), jnp.zeros((), data.dtype))


@register("Embedding", aliases=("embedding",))
def _embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
               sparse_grad=False):
    """Parity: src/operator/tensor/indexing_op.cc Embedding. take → one MXU gather."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ----------------------------------------------------------------------------
# Loss-ish ops
# ----------------------------------------------------------------------------


@register("smooth_l1")
def _smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(
        jnp.abs(data) < 1.0 / s2,
        0.5 * s2 * jnp.square(data),
        jnp.abs(data) - 0.5 / s2,
    )


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1], dtype=logp.dtype)
    return jnp.sum(-onehot * logp)


@register("CTCLoss", aliases=("ctc_loss",), num_outputs=2,
          inputs=("data", "label", "data_lengths", "label_lengths"))
def _ctc_loss(data, label, data_lengths=None, label_lengths=None,
              use_data_lengths=False, use_label_lengths=False, blank_label="first"):
    """CTC forward-backward in log space via lax.scan.

    Parity: src/operator/nn/ctc_loss.cc (warpctc).  data: (T, B, C) logits.
    Blank index 0 (`first`) or C-1 (`last`).  Returns (loss(B,), grads-alias).
    """
    T, B, C = data.shape
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    labels = label.astype(jnp.int32)  # (B, L)
    L = labels.shape[1]
    if use_label_lengths and label_lengths is not None:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        pad = 0 if blank_label == "first" else -1
        lab_len = jnp.sum((labels != pad).astype(jnp.int32), axis=1)
    if use_data_lengths and data_lengths is not None:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((B,), T, jnp.int32)
    # extended label seq: blank l1 blank l2 ... blank  (len S = 2L+1)
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    NEG = jnp.float32(-1e30)
    pos = jnp.arange(S)[None, :]
    # alpha init
    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_lab = jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, first_lab, NEG))

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
    )
    is_blank = ext == blank

    def step(alpha, t):
        shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        allow2 = jnp.logical_and(~is_blank, ~same_as_prev2)
        merged = jnp.logaddexp(alpha, shift1)
        merged = jnp.where(allow2, jnp.logaddexp(merged, shift2), merged)
        emit = jnp.take_along_axis(logp[t], ext, axis=1)
        new_alpha = merged + emit
        # past data length: freeze
        active = (t < dat_len)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return new_alpha, None

    alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    endpos = 2 * lab_len - 1
    a_last = jnp.take_along_axis(alphaT, jnp.maximum(endpos, 0)[:, None], axis=1)[:, 0]
    a_blank = jnp.take_along_axis(alphaT, (2 * lab_len)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(jnp.where(lab_len > 0, a_last, NEG), a_blank)
    loss = -ll
    return loss.astype(data.dtype), jnp.zeros_like(data)


# ----------------------------------------------------------------------------
# RNN (vanilla/LSTM/GRU) as lax.scan — parity: src/operator/rnn.cc:299
# ----------------------------------------------------------------------------


def _rnn_cell_step(mode, x, h, c, wx, wh, bx, bh):
    if mode == "rnn_tanh":
        return jnp.tanh(x @ wx.T + bx + h @ wh.T + bh), c
    if mode == "rnn_relu":
        return jax.nn.relu(x @ wx.T + bx + h @ wh.T + bh), c
    if mode == "lstm":
        gates = x @ wx.T + bx + h @ wh.T + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
        return h2, c2
    if mode == "gru":
        gx = x @ wx.T + bx
        gh = h @ wh.T + bh
        rx, zx, nx = jnp.split(gx, 3, axis=-1)
        rh, zh, nh = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        return (1 - z) * n + z * h, c
    raise ValueError(mode)


def _gates(mode):
    return {"rnn_tanh": 1, "rnn_relu": 1, "lstm": 4, "gru": 3}[mode]


def _unpack_rnn_params(params, mode, num_layers, input_size, state_size,
                       bidirectional):
    """Unflatten the reference's packed parameter vector (rnn-inl.h layout):
    for each layer/direction: W_x (G*H, in), W_h (G*H, H); then all biases."""
    G = _gates(mode)
    dirs = 2 if bidirectional else 1
    offset = 0
    weights = []
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            wx = lax.dynamic_slice(params, (offset,), (G * state_size * in_size,)).reshape(
                G * state_size, in_size)
            offset += G * state_size * in_size
            wh = lax.dynamic_slice(params, (offset,), (G * state_size * state_size,)).reshape(
                G * state_size, state_size)
            offset += G * state_size * state_size
            weights.append((wx, wh))
    biases = []
    for layer in range(num_layers):
        for _ in range(dirs):
            bx = lax.dynamic_slice(params, (offset,), (G * state_size,))
            offset += G * state_size
            bh = lax.dynamic_slice(params, (offset,), (G * state_size,))
            offset += G * state_size
            biases.append((bx, bh))
    return [(wx, wh, bx, bh) for (wx, wh), (bx, bh) in zip(weights, biases)]


def rnn_param_size(mode, num_layers, input_size, state_size, bidirectional=False):
    G = _gates(mode)
    dirs = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else state_size * dirs
        total += dirs * G * state_size * (in_size + state_size + 2)
    return total


@register("RNN", aliases=("rnn",), needs_rng=True, needs_mode=True, num_outputs=3,
          inputs=("data", "parameters", "state", "state_cell"))
def _rnn(key, data, parameters, state, state_cell=None, state_size=0, num_layers=1,
         mode="lstm", bidirectional=False, p=0.0, state_outputs=True,
         projection_size=None, use_sequence_length=False, _mode="predict"):
    """Monolithic RNN op (parity: rnn.cc:299). data: (T, B, I); scan over T.

    Outputs (out(T,B,H*dirs), h_n, c_n).  The whole multi-layer loop is one
    lax.scan-per-layer chain → single fused executable; XLA pipelines the
    per-step matmuls on the MXU.
    """
    T, B, I = data.shape
    dirs = 2 if bidirectional else 1
    layers = _unpack_rnn_params(parameters, mode, num_layers, I, state_size,
                                bidirectional)
    h0 = state  # (L*dirs, B, H)
    c0 = state_cell if state_cell is not None else jnp.zeros_like(state)
    x = data
    h_out, c_out = [], []
    for layer in range(num_layers):
        outs_dir = []
        for d in range(dirs):
            wx, wh, bx, bh = layers[layer * dirs + d]
            hh = h0[layer * dirs + d]
            cc = c0[layer * dirs + d]
            seq = x if d == 0 else jnp.flip(x, axis=0)

            def step(carry, xt, wx=wx, wh=wh, bx=bx, bh=bh):
                h, c = carry
                h2, c2 = _rnn_cell_step(mode, xt, h, c, wx, wh, bx, bh)
                return (h2, c2), h2

            (hT, cT), ys = lax.scan(step, (hh, cc), seq)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs_dir.append(ys)
            h_out.append(hT)
            c_out.append(cT)
        x = outs_dir[0] if dirs == 1 else jnp.concatenate(outs_dir, axis=-1)
        if p > 0.0 and _mode == "train" and layer < num_layers - 1:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    return x, jnp.stack(h_out), jnp.stack(c_out)


# ----------------------------------------------------------------------------
# Attention (reference: src/operator/contrib/transformer.cc:650-780)
# ----------------------------------------------------------------------------


@register("_contrib_interleaved_matmul_selfatt_qk")
def _interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """(T, B, 3*H*D) interleaved qkv → scaled QKᵀ (B*heads, T, T)."""
    T, B, _ = queries_keys_values.shape
    x = queries_keys_values.reshape(T, B, heads, 3, -1)
    q = x[:, :, :, 0, :]
    k = x[:, :, :, 1, :]
    D = q.shape[-1]
    q = jnp.transpose(q, (1, 2, 0, 3)).reshape(B * heads, T, D)
    k = jnp.transpose(k, (1, 2, 0, 3)).reshape(B * heads, T, D)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32)).astype(q.dtype)
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    T, B, _ = queries_keys_values.shape
    x = queries_keys_values.reshape(T, B, heads, 3, -1)
    v = x[:, :, :, 2, :]
    D = v.shape[-1]
    v = jnp.transpose(v, (1, 2, 0, 3)).reshape(B * heads, T, D)
    out = jnp.matmul(attention, v)  # (B*heads, T, D)
    out = out.reshape(B, heads, T, D)
    return jnp.transpose(out, (2, 0, 1, 3)).reshape(T, B, heads * D)


@register("_contrib_arange_like")
def _arange_like(data, start=0.0, step=1.0, axis=None):
    if axis is None:
        n = data.size
        return jnp.arange(start, start + step * n, step, dtype=data.dtype).reshape(
            data.shape)
    n = data.shape[axis]
    return jnp.arange(start, start + step * n, step, dtype=data.dtype)


@register("_contrib_div_sqrt_dim")
def _div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


# ----------------------------------------------------------------------------
# Upsampling / image-ish nn ops
# ----------------------------------------------------------------------------


@register("UpSampling", aliases=("upsampling",))
def _upsampling(*data, scale=2, sample_type="nearest", num_args=1, num_filter=0,
                multi_input_mode="concat", workspace=512):
    x = data[0]
    n, c, h, w = x.shape
    out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    return out


@register("BilinearSampler", aliases=("bilinear_sampler",))
def _bilinear_sampler(data, grid, cudnn_off=False):
    """Parity: src/operator/bilinear_sampler.cc. grid in [-1, 1], NCHW."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yy, xx):
        yy = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
        xx = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
        batch_idx = jnp.arange(n).reshape(n, 1, 1)
        return data[batch_idx, :, yy, xx]  # (n, ho, wo, c)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx_ = wx[..., None]
    wy_ = wy[..., None]
    out = (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_)
           + v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)
    return jnp.transpose(out, (0, 3, 1, 2))


# ----------------------------------------------------------------------------
# Embedding with row_sparse gradient (imperative path)
# ----------------------------------------------------------------------------


def _embedding_sparse_invoke(inputs, attrs, out):
    """Imperative Embedding with ``sparse_grad=True``: the weight gradient
    is produced as a RowSparseNDArray (unique ids, summed cotangent rows)
    instead of a dense table-sized array.

    Parity: indexing_op.cc Embedding's kRowSparseStorage backward.  Only
    active while recording imperatively; under hybridize/JitTrainStep the
    whole graph is one XLA executable and scatter fusion already avoids
    the dense materialization.
    """
    from .. import autograd as _ag
    from ..engine import Engine
    from ..ndarray.ndarray import NDArray
    from ..ndarray import sparse as _sp
    import numpy as _onp

    truthy = attrs.get("sparse_grad") in (True, 1, "1", "true", "True")
    if not truthy or out is not None:
        return NotImplemented
    if not (_ag.is_recording() and inputs[1]._in_graph):
        return NotImplemented
    data, weight = inputs[0], inputs[1]
    ids = data.data().astype(jnp.int32)
    eng = Engine.get()
    out_raw = eng.push(
        lambda: jnp.take(weight.data(), ids, axis=0, mode="clip"),
        op_name="Embedding")
    eng.track(out_raw)
    w_shape = tuple(weight.shape)

    def vjp_fn(cts):
        ct = cts[0]
        flat_ids = _onp.asarray(ids).reshape(-1)
        vals = ct.reshape(-1, ct.shape[-1])
        uniq, inv = _onp.unique(flat_ids, return_inverse=True)
        summed = jnp.zeros((len(uniq), vals.shape[-1]), vals.dtype)
        summed = summed.at[jnp.asarray(inv)].add(vals)
        rsp = _sp.RowSparseNDArray(NDArray(summed), NDArray(uniq), w_shape,
                                   ctx=weight.context, canonical=True)
        return (None, rsp)

    node = _ag.TapeNode(vjp_fn, [data, weight],
                        [(out_raw.shape, out_raw.dtype)],
                        op_name="Embedding")
    res = NDArray(out_raw, ctx=weight.context)
    res._tape_node = node
    res._tape_index = 0
    return res


from .registry import register_invoke_override  # noqa: E402

register_invoke_override("Embedding", _embedding_sparse_invoke)
