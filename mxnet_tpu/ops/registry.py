"""Operator registry + imperative dispatcher.

Reference: nnvm's ``Op`` registry (``NNVM_REGISTER_OP``, 500 ops in
``src/operator/``) and the imperative hot path ``MXImperativeInvokeEx →
Imperative::Invoke → PushFCompute`` (``src/imperative/imperative.cc:89``,
``imperative_utils.h:395``).

TPU-native design: an op is a pure JAX function ``forward(*tensors, **attrs)``
returning one array or a tuple.  Per (op, static attrs, input-field set) we
build ONE jitted callable — XLA then caches compiled executables by input
shape/dtype, which replaces both the reference's per-op FCompute kernels and
its engine push: dispatching the jitted callable enqueues the kernel on the
PJRT stream asynchronously.  Shape/dtype inference (reference
``FInferShape/FInferType``) falls out of ``jax.eval_shape`` on the same
function, so ops can never disagree with their inference — a class of
reference bugs gone by design.

RNG ops declare ``needs_rng``: the dispatcher prepends a fresh threefry key
from the global ``mxnet_tpu.random`` state (reference: ``kRandom`` resource,
``src/resource.cc``).  Mode-aware ops (dropout, BN) declare ``needs_mode`` and
receive ``_mode='train'|'predict'`` as a static attr.

Sharding propagation (mxnet_tpu/sharding/): every dispatch route ends in
``jax.jit``, and jit specializes per input *sharding* as well as per
shape/dtype — GSPMD then partitions the computation, so an op over
``nd.shard``-ed inputs runs as ONE multi-device executable with sharded
outputs; no registry-side bookkeeping is needed.  The two places where
that implicit keying is not enough own it explicitly: taped bulk
segments pin their lowering, so ``engine.BulkSegment.flush`` folds the
ext-input placements into the segment-cache key, and in-trace
re-annotation goes through the ``_sharding_constraint`` op (ops/misc.py)
whose NamedSharding attr is hashable and thus part of ``_jitted``'s key.
"""
from __future__ import annotations

import functools
import inspect
import time

import jax

from ..base import MXNetError
from .. import autograd
from .. import compile_cache as _ccache
from ..engine import Engine
from ..telemetry import metrics as _metrics

_REGISTRY = {}
_ALIASES = {}


class OpReg:
    __slots__ = ("name", "forward", "needs_rng", "needs_mode", "num_outputs",
                 "doc", "input_names", "variadic", "attr_names")

    def __init__(self, name, forward, needs_rng=False, needs_mode=False,
                 num_outputs=1, inputs=None):
        self.name = name
        self.forward = forward
        self.needs_rng = needs_rng
        self.needs_mode = needs_mode
        self.num_outputs = num_outputs
        self.doc = forward.__doc__ or ""
        self.input_names, self.variadic = self._infer_inputs(forward, inputs)
        self.attr_names = self._infer_attrs(forward)

    def _infer_attrs(self, fn):
        """Ordered non-tensor parameter names (for positional attr args)."""
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            return ()
        names = [p.name for p in sig.parameters.values()
                 if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                               inspect.Parameter.KEYWORD_ONLY)]
        return tuple(n for n in names
                     if n != "key" and n not in self.input_names)

    def _infer_inputs(self, fn, explicit):
        """Ordered tensor-parameter names.  Default: leading params without
        defaults.  Ops with optional/late tensor params declare ``inputs=``."""
        try:
            sig = inspect.signature(fn)
        except (TypeError, ValueError):
            return (), True
        params = list(sig.parameters.values())
        if self.needs_rng and params and params[0].name == "key":
            params = params[1:]
        if any(p.kind == inspect.Parameter.VAR_POSITIONAL for p in params):
            return (), True
        if explicit is not None:
            return tuple(explicit), False
        names = []
        for p in params:
            if p.default is inspect.Parameter.empty:
                names.append(p.name)
            else:
                break
        return tuple(names), False


def register(name, needs_rng=False, needs_mode=False, num_outputs=1, aliases=(),
             inputs=None):
    """Decorator: register a JAX forward under an MXNet op name."""

    def deco(fn):
        if name in _REGISTRY:
            raise MXNetError("op %s already registered" % name)
        _REGISTRY[name] = OpReg(name, fn, needs_rng, needs_mode, num_outputs,
                                inputs=inputs)
        for a in aliases:
            _ALIASES[a] = name
        return fn

    return deco


def alias(new, old):
    _ALIASES[new] = old


def get(name):
    reg = _REGISTRY.get(name)
    if reg is None:
        reg = _REGISTRY.get(_ALIASES.get(name, ""))
    if reg is None:
        raise MXNetError("operator %r is not registered" % (name,))
    return reg


def list_ops(detail=False):
    """Registered op names, primaries and aliases together.

    ``detail=False`` (default): sorted list of names.
    ``detail=True``: sorted list of ``(name, num_outputs, needs_rng,
    needs_mode)`` tuples — aliases report their target's metadata, so the
    registry's whole public surface is introspectable (used by the RC3xx
    consistency pass and ``tools/mxlint.py``).
    """
    if not detail:
        return sorted(set(_REGISTRY) | set(_ALIASES))
    out = []
    for name in sorted(set(_REGISTRY) | set(_ALIASES)):
        reg = _REGISTRY.get(name) or _REGISTRY.get(_ALIASES.get(name, ""))
        if reg is None:
            continue  # dangling alias; RC3xx reports it, don't crash here
        out.append((name, reg.num_outputs, reg.needs_rng, reg.needs_mode))
    return out


def _freeze(v):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


@functools.lru_cache(maxsize=None)
def _op_run(name, fields, attrs_key):
    """Raw (unjitted) runner per (op, input fields, static attrs).

    Shared by the eager path (jitted whole in :func:`_jitted`) and the
    bulking path (inlined into one segment-wide jit) so both dispatch
    routes trace the exact same python callable.
    """
    reg = get(name)
    attrs = dict(attrs_key)

    if reg.variadic:
        def run(*arrays):
            out = reg.forward(*arrays, **attrs)
            return out if isinstance(out, tuple) else (out,)
    else:
        def run(*arrays):
            if reg.needs_rng:
                kw = dict(zip(("key",) + fields, arrays))
            else:
                kw = dict(zip(fields, arrays))
            out = reg.forward(**kw, **attrs)
            return out if isinstance(out, tuple) else (out,)

    run.__name__ = name.lstrip("_") or name
    return run


@functools.lru_cache(maxsize=None)
def _jitted(name, fields, attrs_key):
    """One jitted callable per (op, input fields, static attrs).

    This cache is the TPU analogue of the reference's per-op FCompute
    dispatch table + CachedOp executable cache (cached_op.cc:417): XLA adds
    the per-shape/dtype level underneath automatically.
    """
    jitted = jax.jit(_op_run(name, fields, attrs_key))
    try:
        # marks this callable as cacheable for the lazy tape's jitted
        # backward (autograd._node_backward)
        jitted._mx_stable = True
    except Exception:
        pass
    return jitted


@functools.lru_cache(maxsize=None)
def _out_avals(name, fields, attrs_key, aval_key):
    """Output ShapeDtypeStructs for one deferred op (lazy NDArray shape/
    dtype come from here — same ``jax.eval_shape`` mechanism the registry
    already uses for inference, so bulked ops can't disagree with it)."""
    run = _op_run(name, fields, attrs_key)
    args = [jax.ShapeDtypeStruct(s, d) for s, d in aval_key]
    return tuple(jax.eval_shape(run, *args))


def _telemetry_collector():
    """Export the executable-cache aggregates at snapshot time.

    ``_jitted``'s lru_cache already counts every eager-path resolution
    (one per invoke), so telemetry reads the totals for free instead of
    inc'ing counters on the dispatch hot path.
    """
    info = _jitted.cache_info()
    _metrics.counter("mxnet_jit_cache_hits_total",
                     help="jitted-callable cache hits (op, fields, attrs)"
                     ).set(info.hits)
    _metrics.counter("mxnet_jit_cache_misses_total",
                     help="jitted-callable cache misses").set(info.misses)
    _metrics.gauge("mxnet_jit_cache_size",
                   help="distinct jitted callables held"
                   ).set(info.currsize)


_metrics.register_collector(_telemetry_collector)


# jitted fn -> last observed executable-cache size (-1: fn has no
# probe).  Keeping the last size here makes the steady-state compile
# check one dict hit + one _cache_size() instead of probing twice per
# dispatch; entries live exactly as long as the _jitted cache does.
_exec_cache_sizes = {}


def _push_op(eng, fn, datas, name):
    """Eager push of a jitted op with compile tracking.

    XLA compiles lazily on the first call per (shape, dtype): a growth
    of ``fn._cache_size()`` across the push means this call paid a
    trace+compile, so its wall time goes to ``mxnet_compile_seconds``
    and the retrace watchdog (``fn`` identifies the op signature — one
    jitted callable per (op, fields, attrs) via the ``_jitted`` cache).
    Never wraps ``fn`` itself: autograd and the segment cache key on
    the bare callable's identity.
    """
    if not _metrics._ENABLED:
        return eng.push(lambda: fn(*datas), op_name=name)
    n0 = _exec_cache_sizes.get(fn)
    if n0 is None:
        try:
            n0 = fn._cache_size()
        except Exception:
            n0 = -1  # non-jit callable or jax without the probe
        _exec_cache_sizes[fn] = n0
    if n0 < 0:
        return eng.push(lambda: fn(*datas), op_name=name)
    t0 = time.perf_counter()
    disk0 = _ccache.persistent_hits()
    outs = eng.push(lambda: fn(*datas), op_name=name)
    n1 = fn._cache_size()
    if n1 > n0:
        _exec_cache_sizes[fn] = n1
        if _ccache.persistent_hits() - disk0 >= n1 - n0:
            # the executable(s) loaded from the persistent disk cache — a
            # warm start, already counted by mxnet_compile_cache_hits_total;
            # keep it out of mxnet_compile_seconds and the retrace watchdog
            pass
        else:
            _metrics.record_compile(name, fn, time.perf_counter() - t0,
                                    n=n1 - n0)
    return outs


def _prep(reg, datas, attrs, fields):
    """Normalize (datas, attrs, fields) and resolve the jitted callable."""
    # drop unset attrs: every registered forward defaults its optional
    # params, so a None-valued attr is the default spelled loudly — keeping
    # it would only fragment the _jitted/_out_avals cache keys
    attrs = {k: v for k, v in (attrs or {}).items() if v is not None}
    if reg.needs_mode and "_mode" not in attrs:
        attrs["_mode"] = "train" if autograd.is_training() else "predict"
    from .. import amp as _amp

    if _amp.is_active():
        # AMP's dispatch-time dtype rewrite (amp/__init__.py) — the
        # imperative+trace analogue of the reference's low_precision_pass
        datas = _amp.transform_inputs(reg.name, tuple(datas))
    n_rng = 0
    if reg.needs_rng:
        from .. import random as _random

        datas = (_random.next_key(),) + tuple(datas)
        n_rng = 1
    if fields is None:
        fields = reg.input_names[: len(datas) - n_rng]
    fn = _jitted(reg.name, tuple(fields), _freeze(attrs))
    return fn, tuple(datas), n_rng


def invoke_raw(name, datas, attrs=None, fields=None):
    """Invoke on raw jax arrays → (outputs_tuple, vjp_or_None, n_rng)."""
    reg = get(name)
    fn, datas, n_rng = _prep(reg, tuple(datas), attrs, fields)
    eng = Engine.get()
    if autograd.is_recording():
        outs, vjp = eng.push(lambda: jax.vjp(fn, *datas), op_name=name)
    else:
        outs = _push_op(eng, fn, datas, name)
        vjp = None
    for o in outs:
        eng.track(o)
    return outs, vjp, n_rng


def invoke_fn(fn, inputs, op_name="custom", n_outputs=None):
    """Invoke an ad-hoc traceable ``fn(*raw arrays) → tuple`` on NDArrays
    with full tape integration (recording, prim for higher-order grads).

    The escape hatch behind the control-flow ops (`lax.scan`-built
    closures have no registry entry) — the TPU analogue of the reference's
    stateful control-flow ops executing sub-CachedOps
    (src/operator/control_flow.cc).
    """
    from ..ndarray.ndarray import NDArray

    datas = tuple(x.data() for x in inputs)
    recording = autograd.is_recording() and any(x._in_graph for x in inputs)
    eng = Engine.get()
    node = None
    outs = eng.push(lambda: fn(*datas), op_name=op_name)
    if recording:
        # lazy tape: only the primal (fn, inputs) is recorded; backward
        # runs through a cached jitted vjp (autograd._prim_backward)
        node = autograd.TapeNode(
            None,
            list(inputs),
            [(o.shape, o.dtype) for o in outs],
            op_name=op_name,
            prim=(fn, datas, 0),
        )
    for o in outs:
        eng.track(o)
    ctx = inputs[0].context if inputs else None
    cls = inputs[0]._op_result_cls if inputs else NDArray
    results = []
    for i, o in enumerate(outs):
        arr = cls(o, ctx=ctx)
        if node is not None:
            arr._tape_node = node
            arr._tape_index = i
        results.append(arr)
    return results


# op-specific imperative overrides (e.g. Embedding's row_sparse gradient);
# a handler returns NotImplemented to fall through to the generic path
_INVOKE_OVERRIDES = {}


def register_invoke_override(name, handler):
    _INVOKE_OVERRIDES[name] = handler


def _try_bulk(reg, inputs, attrs, out, fields, eng):
    """Defer one imperative op into the current bulk segment.

    Returns the op result (lazy NDArrays promised by the segment), or
    ``NotImplemented`` to fall through to the eager path.  Non-deferrable
    ops (RNG-keyed, AMP-rewritten, non-NDArray operands) conservatively
    flush the open segment first so program order is preserved.
    """
    from ..ndarray.ndarray import NDArray

    size = eng.bulk_size()
    if size <= 0:
        return NotImplemented
    if reg.needs_rng:
        eng.flush_bulk("rng:%s" % reg.name)
        return NotImplemented
    if not inputs or any(not isinstance(x, NDArray) for x in inputs):
        eng.flush_bulk("nondeferrable:%s" % reg.name)
        return NotImplemented
    from .. import amp as _amp

    if _amp.is_active():
        eng.flush_bulk("amp:%s" % reg.name)
        return NotImplemented
    attrs = {k: v for k, v in (attrs or {}).items() if v is not None}
    if reg.needs_mode and "_mode" not in attrs:
        attrs["_mode"] = "train" if autograd.is_training() else "predict"
    if fields is None:
        fields = reg.input_names[: len(inputs)]
    fields = tuple(fields)
    try:
        attrs_key = _freeze(attrs)
        hash(attrs_key)
    except TypeError:
        eng.flush_bulk("unhashable_attrs:%s" % reg.name)
        return NotImplemented

    seg = eng.current_segment(size)
    handles = []
    aval_key = []
    prim_datas = []
    for x in inputs:
        p = x._pending
        if p is not None and p.value is None and not p.failed \
                and p.segment is seg:
            handles.append(("v", p))
            aval_key.append((tuple(p.aval.shape), p.aval.dtype))
            prim_datas.append(p)
        else:
            d = x.data()  # materializes refs from older segments
            if isinstance(d, jax.core.Tracer):
                # inside a jit/eval_shape trace (hybridize, control flow):
                # deferring would leak the tracer past its trace — run
                # eagerly, which simply inlines into the enclosing trace
                return NotImplemented
            handles.append(("x", d, x))  # x: supplier, for buffer donation
            aval_key.append((tuple(d.shape), d.dtype))
            prim_datas.append(d)
    try:
        out_avals = _out_avals(reg.name, fields, attrs_key, tuple(aval_key))
    except Exception:
        return NotImplemented  # let the eager path raise the canonical error

    run_fn = _op_run(reg.name, fields, attrs_key)
    refs = seg.defer((reg.name, fields, attrs_key), run_fn, handles,
                     out_avals)
    eng.stats.bulk_ops += 1
    ctx = inputs[0].context
    cls = inputs[0]._op_result_cls
    results = [cls(r, ctx=ctx) for r in refs]
    # output vars join the segment's write set: version bumps happened at
    # construction/adopt exactly as eager, but a failed flush must still be
    # able to poison every promised output (async rethrow contract)
    seg.add_write_vars([a._var for a in results])
    if autograd.is_recording() and any(x._in_graph for x in inputs):
        # segment-spanning tape: record against the SAME jitted callable
        # the eager path would store (identical _mx_bwd vjp executable →
        # bitwise-identical grads); primals that are still promises
        # (_BulkRef) resolve lazily at backward time
        node = autograd.TapeNode(
            None,
            list(inputs),
            [(tuple(a.shape), a.dtype) for a in out_avals],
            op_name=reg.name,
            prim=(_jitted(reg.name, fields, attrs_key),
                  tuple(prim_datas), 0),
        )
        for i, arr in enumerate(results):
            arr._tape_node = node
            arr._tape_index = i
        seg.taped = True  # flush compiles the exact (bitwise-eager) build
    if seg.cap and seg.n_ops >= seg.cap:
        seg.flush("max_node")
    if out is not None:
        outs_list = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs_list, results):
            dst._adopt(src)
        return out
    if len(results) == 1:
        return results[0]
    return results


def invoke(name, inputs, attrs=None, out=None, fields=None):
    """Imperative invoke on NDArrays (parity: Imperative::Invoke).

    Records a tape node when autograd is recording and any input is in-graph.
    When bulking is active (engine.bulk_size() > 0) deferrable ops join the
    open BulkSegment instead and return lazy NDArrays.
    """
    from ..ndarray.ndarray import NDArray

    eng = Engine.get()
    handler = _INVOKE_OVERRIDES.get(name)
    if handler is not None:
        # overrides run op-specific host logic the segment can't see
        eng.flush_bulk("override:%s" % name)
        res = handler(inputs, attrs or {}, out)
        if res is not NotImplemented:
            return res

    reg = get(name)
    res = _try_bulk(reg, inputs, attrs, out, fields, eng)
    if res is not NotImplemented:
        return res
    datas = tuple(x.data() for x in inputs)
    recording = autograd.is_recording() and any(x._in_graph for x in inputs)
    node = None
    fn, datas2, n_rng = _prep(reg, datas, attrs, fields)
    outs = _push_op(eng, fn, datas2, name)
    if recording:
        # lazy tape (reference records AGInfo nodes, not gradients):
        # the forward runs through its cached jitted executable as usual
        # and the node stores only (fn, primals).  The backward pass
        # re-linearizes through ONE cached jitted vjp executable per
        # (op, shapes) — recording adds no tracing cost per call, and
        # backward stops re-tracing jax.vjp on every invocation.
        node = autograd.TapeNode(
            None,
            list(inputs),
            [(o.shape, o.dtype) for o in outs],
            skip_grad_inputs=n_rng,
            op_name=name,
            prim=(fn, datas2, n_rng),
        )
    for o in outs:
        eng.track(o)

    ctx = inputs[0].context if inputs else None
    # op results adopt the frontend class of the first input, so mx.np
    # arrays stay mx.np arrays through every registry op
    cls = inputs[0]._op_result_cls if inputs else NDArray
    results = []
    for i, o in enumerate(outs):
        arr = cls(o, ctx=ctx)
        if node is not None:
            arr._tape_node = node
            arr._tape_index = i
        results.append(arr)
    if out is not None:
        outs_list = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs_list, results):
            dst._adopt(src)
        return out
    if len(results) == 1:
        return results[0]
    return results
