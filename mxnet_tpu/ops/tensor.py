"""Tensor operators (elementwise / broadcast / reduce / index / init / linalg).

Reference: ``src/operator/tensor/`` — 36 .cc/.cu files of mshadow kernels
(elemwise_binary*, broadcast_reduce*, indexing_op, matrix_op, ordering_op,
init_op, dot, la_op).  Here every op is a closed-form JAX/XLA expression;
gradients come from XLA's autodiff of the same expression, so the reference's
hand-written ``FGradient`` entries (``elemwise_binary_op_basic.cc`` etc.)
have no counterpart to maintain.

MXNet semantics preserved where they differ from NumPy:
* ``sum/mean/...`` accept ``axis=()`` meaning ALL axes (legacy nd semantics),
  plus ``exclude`` to invert the axis set (``broadcast_reduce_op.h``).
* elementwise binary ops require equal shapes; ``broadcast_*`` variants do
  NumPy broadcasting (``elemwise_binary_broadcast_op.h``).
* ``Reshape`` supports the magic codes 0/-1/-2/-3/-4 (``matrix_op-inl.h``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ----------------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        axes = tuple(range(ndim))
    elif isinstance(axis, (tuple, list)):
        axes = tuple(a % ndim for a in axis) if axis else tuple(range(ndim))
    else:
        axes = (int(axis) % ndim,)
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _reduce(fn_name):
    jfn = getattr(jnp, fn_name)

    def op(x, axis=None, keepdims=False, exclude=False):
        axes = _norm_axis(axis, x.ndim, exclude)
        return jfn(x, axis=axes if axes else None, keepdims=keepdims)

    return op


# ----------------------------------------------------------------------------
# elementwise binary (same-shape) + scalar + broadcast variants
# ----------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
    "equal": lambda a, b: (a == b).astype(a.dtype),
    "not_equal": lambda a, b: (a != b).astype(a.dtype),
    "greater": lambda a, b: (a > b).astype(a.dtype),
    "greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "lesser": lambda a, b: (a < b).astype(a.dtype),
    "lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "logical_and": lambda a, b: jnp.logical_and(a, b).astype(a.dtype),
    "logical_or": lambda a, b: jnp.logical_or(a, b).astype(a.dtype),
    "logical_xor": lambda a, b: jnp.logical_xor(a, b).astype(a.dtype),
}

for _name, _fn in _BINARY.items():
    # elemwise_* (same shape) — internal names match reference (_plus etc.)
    _ew_name = {
        "add": "elemwise_add", "sub": "elemwise_sub", "mul": "elemwise_mul",
        "div": "elemwise_div",
    }.get(_name, "_" + _name)
    register(_ew_name, aliases=("_" + _name,) if _ew_name != "_" + _name else ())(
        (lambda f: lambda lhs, rhs: f(lhs, rhs))(_fn)
    )
    register("broadcast_" + _name)((lambda f: lambda lhs, rhs: f(lhs, rhs))(_fn))

alias("broadcast_plus", "broadcast_add")
alias("broadcast_minus", "broadcast_sub")

_SCALAR_BINARY = {
    "_plus_scalar": lambda x, scalar: x + scalar,
    "_minus_scalar": lambda x, scalar: x - scalar,
    "_rminus_scalar": lambda x, scalar: scalar - x,
    "_mul_scalar": lambda x, scalar: x * scalar,
    "_div_scalar": lambda x, scalar: x / scalar,
    "_rdiv_scalar": lambda x, scalar: scalar / x,
    "_mod_scalar": lambda x, scalar: jnp.mod(x, scalar),
    "_rmod_scalar": lambda x, scalar: jnp.mod(scalar, x),
    "_power_scalar": lambda x, scalar: jnp.power(x, scalar),
    "_rpower_scalar": lambda x, scalar: jnp.power(scalar, x),
    "_maximum_scalar": lambda x, scalar: jnp.maximum(x, scalar),
    "_minimum_scalar": lambda x, scalar: jnp.minimum(x, scalar),
    "_equal_scalar": lambda x, scalar: (x == scalar).astype(x.dtype),
    "_not_equal_scalar": lambda x, scalar: (x != scalar).astype(x.dtype),
    "_greater_scalar": lambda x, scalar: (x > scalar).astype(x.dtype),
    "_greater_equal_scalar": lambda x, scalar: (x >= scalar).astype(x.dtype),
    "_lesser_scalar": lambda x, scalar: (x < scalar).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, scalar: (x <= scalar).astype(x.dtype),
    "_logical_and_scalar": lambda x, scalar: jnp.logical_and(x, scalar).astype(x.dtype),
    "_logical_or_scalar": lambda x, scalar: jnp.logical_or(x, scalar).astype(x.dtype),
    "_logical_xor_scalar": lambda x, scalar: jnp.logical_xor(x, scalar).astype(x.dtype),
}
for _name, _fn in _SCALAR_BINARY.items():
    # inputs declared explicitly: ``scalar`` is a static attr, not a tensor
    register(_name, inputs=("x",))(_fn)

# ----------------------------------------------------------------------------
# elementwise unary
# ----------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "ceil": jnp.ceil, "floor": jnp.floor,
    "rint": jnp.rint, "round": jnp.round, "trunc": jnp.trunc, "fix": jnp.trunc,
    "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "log1p": jnp.log1p, "expm1": jnp.expm1, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x), "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x), "square": jnp.square,
    "reciprocal": lambda x: 1.0 / x, "negative": jnp.negative,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "sigmoid": jax.nn.sigmoid, "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu, "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
}
for _name, _fn in _UNARY.items():
    register(_name)((lambda f: lambda data: f(data))(_fn))

alias("_copy", "identity")
register("identity")(lambda data: data)
register("stop_gradient", aliases=("BlockGrad", "make_loss_grad_stop"))(
    lambda data: lax.stop_gradient(data)
)
register("make_loss")(lambda data: data)
# int64 per the reference ABI when 64-bit index math is on
# (MXNET_INT64_TENSOR_SIZE=1 -> x64); int32 otherwise — asking jnp for
# int64 with x64 off just truncates with a UserWarning on every call
def _index_dtype():
    import jax

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


register("shape_array")(
    lambda data: jnp.asarray(data.shape, dtype=_index_dtype()))
register("size_array")(
    lambda data: jnp.asarray([data.size], dtype=_index_dtype()))

# ----------------------------------------------------------------------------
# casts
# ----------------------------------------------------------------------------


@register("cast", aliases=("Cast",))
def _cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register("amp_cast")
def _amp_cast(data, dtype="float16"):
    return data.astype(jnp.dtype(dtype))


@register("amp_multicast", num_outputs=-1)
def _amp_multicast(*data, num_outputs=1):
    widest = jnp.result_type(*[d.dtype for d in data])
    return tuple(d.astype(widest) for d in data)


# ----------------------------------------------------------------------------
# reductions
# ----------------------------------------------------------------------------

for _name in ("sum", "mean", "prod", "max", "min", "nansum", "nanprod"):
    register(_name, aliases=("sum_axis",) if _name == "sum" else ())(_reduce(_name))


@register("norm")
def _norm(data, ord=2, axis=None, keepdims=False):
    axes = _norm_axis(axis, data.ndim) if axis is not None else None
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axes, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=keepdims))


@register("argmax")
def _argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmin")
def _argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmax_channel")
def _argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register("cumsum")
def _cumsum(a, axis=None, dtype=None):
    return jnp.cumsum(a, axis=axis, dtype=dtype)


@register("logsumexp")
def _logsumexp(data, axis=None, keepdims=False):
    axes = _norm_axis(axis, data.ndim) if axis is not None else None
    return jax.scipy.special.logsumexp(data, axis=axes, keepdims=keepdims)


# ----------------------------------------------------------------------------
# shape manipulation
# ----------------------------------------------------------------------------


def _infer_reshape(src_shape, target):
    """MXNet Reshape magic codes 0/-1/-2/-3/-4 (reference matrix_op-inl.h)."""
    out = []
    src = list(src_shape)
    i = 0  # index into src
    t = 0
    target = list(target)
    while t < len(target):
        d = target[t]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1  # placeholder, fixed below
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            d1, d2 = target[t + 1], target[t + 2]
            cur = src[i]; i += 1
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); t += 2
        else:
            out.append(d); i += 1 if i < len(src) else 0
        t += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src_shape:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


@register("reshape", aliases=("Reshape",))
def _reshape(data, shape=None, reverse=False):
    tgt = _infer_reshape(data.shape, shape)
    return jnp.reshape(data, tgt)


@register("reshape_like")
def _reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("flatten", aliases=("Flatten",))
def _flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose")
def _transpose(data, axes=None):
    if axes is None or axes == ():
        axes = tuple(reversed(range(data.ndim)))
    return jnp.transpose(data, axes)


@register("swapaxes", aliases=("SwapAxis",))
def _swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("expand_dims")
def _expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def _squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("depth_to_space")
def _depth_to_space(data, block_size=2):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, b, b, c // (b * b), h, w))
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return jnp.reshape(x, (n, c // (b * b), h * b, w * b))


@register("space_to_depth")
def _space_to_depth(data, block_size=2):
    n, c, h, w = data.shape
    b = block_size
    x = jnp.reshape(data, (n, c, h // b, b, w // b, b))
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return jnp.reshape(x, (n, c * b * b, h // b, w // b))


@register("broadcast_to")
def _broadcast_to(data, shape=None):
    tgt = tuple(s if t == 0 else t for s, t in zip(data.shape, shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_like")
def _broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(data, axis=(), size=()):
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    sizes = size if isinstance(size, (tuple, list)) else (size,)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register("tile")
def _tile(data, reps=()):
    return jnp.tile(data, reps)


@register("repeat")
def _repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("reverse", aliases=("flip",))
def _reverse(data, axis=()):
    axes = axis if isinstance(axis, (tuple, list)) else (axis,)
    return jnp.flip(data, axis=axes)


@register("concat", aliases=("Concat",), num_outputs=1)
def _concat(*data, dim=1, num_args=None):
    return jnp.concatenate(data, axis=dim)


@register("stack")
def _stack(*data, axis=0, num_args=None):
    return jnp.stack(data, axis=axis)


@register("split", aliases=("SliceChannel",), num_outputs=-1)
def _split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("split_v2", num_outputs=-1)
def _split_v2(data, indices=(), axis=0, squeeze_axis=False, sections=0):
    if sections:
        parts = jnp.split(data, sections, axis=axis)
    else:
        parts = jnp.split(data, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("slice")
def _slice(data, begin=(), end=(), step=()):
    slices = []
    step = step or (None,) * len(begin)
    for i, (b, e) in enumerate(zip(begin, end)):
        s = step[i] if i < len(step) else None
        slices.append(slice(b, e, s))
    return data[tuple(slices)]


@register("slice_axis")
def _slice_axis(data, axis=0, begin=0, end=None):
    sl = [slice(None)] * data.ndim
    sl[axis] = slice(begin, end)
    return data[tuple(sl)]


@register("slice_like")
def _slice_like(data, shape_like, axes=()):
    axes = axes if axes else tuple(range(min(data.ndim, shape_like.ndim)))
    sl = [slice(None)] * data.ndim
    for a in axes:
        sl[a] = slice(0, shape_like.shape[a])
    return data[tuple(sl)]


@register("pad", aliases=("Pad",))
def _pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)


@register("clip")
def _clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


# ----------------------------------------------------------------------------
# indexing / gather / scatter
# ----------------------------------------------------------------------------


@register("take")
def _take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
        mode = "clip"
    return jnp.take(a, idx, axis=axis, mode="clip")


@register("pick")
def _pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, data.shape[axis] - 1)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def _gather_nd(data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd")
def _scatter_nd(data, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_scatter_set_nd")
def _scatter_set_nd(lhs, rhs, indices, shape=()):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return lhs.at[tuple(idx[i] for i in range(m))].set(rhs)


@register("one_hot")
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value


@register("where")
def _where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("boolean_mask_fill")
def _boolean_mask_fill(data, mask, value=0.0):
    """Static-shape stand-in for boolean_mask (dynamic shapes don't jit).

    The mask selects along leading axes (reference boolean_mask semantics),
    so it broadcasts over data's trailing dims.
    """
    m = mask.astype(bool).reshape(
        mask.shape + (1,) * (data.ndim - mask.ndim))
    return jnp.where(m, data, value)


# ----------------------------------------------------------------------------
# ordering
# ----------------------------------------------------------------------------


@register("sort")
def _sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort")
def _argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


@register("topk", num_outputs=-1)
def _topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    x = jnp.moveaxis(data, axis, -1)
    if is_ascend:
        vals, idx = lax.top_k(-x, k)
        vals = -vals
    else:
        vals, idx = lax.top_k(x, k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.dtype(dtype))
    if ret_typ == "value":
        return (vals,)
    if ret_typ == "both":
        return (vals, idx)
    return (idx,)


# ----------------------------------------------------------------------------
# init ops (no-input)
# ----------------------------------------------------------------------------


@register("_zeros")
def _zeros(shape=(), dtype="float32"):
    return jnp.zeros(shape, jnp.dtype(dtype))


@register("_ones")
def _ones(shape=(), dtype="float32"):
    return jnp.ones(shape, jnp.dtype(dtype))


@register("_full")
def _full(shape=(), value=0.0, dtype="float32"):
    return jnp.full(shape, value, jnp.dtype(dtype))


@register("_arange")
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32"):
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("_linspace")
def _linspace(start=0.0, stop=1.0, num=50, endpoint=True, dtype="float32"):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint, dtype=jnp.dtype(dtype))


@register("_eye")
def _eye(N=0, M=0, k=0, dtype="float32"):
    return jnp.eye(int(N), int(M) or None, k=int(k), dtype=jnp.dtype(dtype))


@register("zeros_like")
def _zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def _ones_like(data):
    return jnp.ones_like(data)


@register("full_like")
def _full_like(data, fill_value=0.0):
    return jnp.full_like(data, fill_value)


@register("diag")
def _diag(data, k=0):
    return jnp.diag(data, k=k) if data.ndim <= 2 else jnp.diagonal(data, offset=k)


# ----------------------------------------------------------------------------
# linalg: dot / batch_dot / einsum + la_op subset
# ----------------------------------------------------------------------------


@register("dot")
def _dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a and lhs.ndim == 2 else lhs
    b = rhs.T if transpose_b and rhs.ndim == 2 else rhs
    if transpose_a and lhs.ndim > 2:
        a = jnp.moveaxis(lhs, 0, -1)
    if transpose_b and rhs.ndim > 2:
        b = jnp.moveaxis(rhs, -1, 0)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot")
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register("_npi_einsum", aliases=("einsum",))
def _einsum(*operands, subscripts=""):
    return jnp.einsum(subscripts, *operands)


@register("_linalg_gemm2", aliases=("linalg_gemm2",))
def _linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("_linalg_gemm", aliases=("linalg_gemm",))
def _linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
                 axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("_linalg_syrk", aliases=("linalg_syrk",))
def _linalg_syrk(A, transpose=False, alpha=1.0):
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("_linalg_potrf", aliases=("linalg_potrf",))
def _linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("_linalg_trsm", aliases=("linalg_trsm",))
def _linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose else A
    low = lower != transpose
    if rightside:
        x = jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(a, -1, -2), jnp.swapaxes(alpha * B, -1, -2), lower=not low
        )
        return jnp.swapaxes(x, -1, -2)
    return jax.scipy.linalg.solve_triangular(a, alpha * B, lower=low)


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def _linalg_sumlogdiag(A):
    return jnp.sum(jnp.log(jnp.diagonal(A, axis1=-2, axis2=-1)), axis=-1)


@register("_linalg_extractdiag", aliases=("linalg_extractdiag",))
def _linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("_linalg_makediag", aliases=("linalg_makediag",))
def _linalg_makediag(A, offset=0):
    eye = jnp.eye(A.shape[-1] + abs(offset), dtype=A.dtype)
    return A[..., None] * eye[: A.shape[-1]] if offset == 0 else jnp.zeros(())


@register("_linalg_svd", aliases=("linalg_svd",), num_outputs=3)
def _linalg_svd(A):
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return u, s, vt


@register("_linalg_inverse", aliases=("linalg_inverse",))
def _linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("_linalg_det", aliases=("linalg_det",))
def _linalg_det(A):
    return jnp.linalg.det(A)


@register("khatri_rao")
def _khatri_rao(*args, num_args=None):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("ir,jr->ijr", out, m).reshape(-1, out.shape[-1])
    return out


# ----------------------------------------------------------------------------
# sequence ops (reference: src/operator/sequence_*.cc)
# ----------------------------------------------------------------------------


@register("SequenceMask", aliases=("sequence_mask",),
          inputs=("data", "sequence_length"))
def _sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0,
                   axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :]  # (T, B)
    if axis == 1:
        mask = mask.T
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    shape[1 - axis] = data.shape[1 - axis]
    mask = jnp.reshape(mask, shape)
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceLast", aliases=("sequence_last",),
          inputs=("data", "sequence_length"))
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length - 1).astype(jnp.int32)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    return jnp.take_along_axis(
        moved, idx.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0
    )[0]


@register("SequenceReverse", aliases=("sequence_reverse",),
          inputs=("data", "sequence_length"))
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    lengths = sequence_length[None, :].astype(jnp.int32)
    rev_idx = jnp.where(steps < lengths, lengths - 1 - steps, steps)
    moved = data  # (T, B, ...)
    idx = rev_idx.reshape((T, -1) + (1,) * (moved.ndim - 2))
    idx = jnp.broadcast_to(idx, moved.shape)
    return jnp.take_along_axis(moved, idx, axis=0)


# ----------------------------------------------------------------------------
# linalg wave 2 (parity: src/operator/tensor/la_op.cc — LAPACK-backed ops;
# here XLA's native linalg lowerings, which map to MXU-tiled kernels)
# ----------------------------------------------------------------------------


@register("_linalg_extracttrian")
def _linalg_extracttrian(A, offset=0, lower=True):
    n = A.shape[-1]
    k = -int(offset) if lower else int(offset)
    idx = jnp.tril_indices(n, k) if lower else jnp.triu_indices(n, k)
    return A[..., idx[0], idx[1]]


@register("_linalg_maketrian")
def _linalg_maketrian(A, offset=0, lower=True):
    L = A.shape[-1]
    k = -int(offset) if lower else int(offset)
    # tril(n, k<=0) holds m(m+1)/2 entries with m = n - |k| (triu(n, k>=0)
    # symmetric), so n is closed-form from L
    m = int(round(((8 * L + 1) ** 0.5 - 1) / 2))
    if m * (m + 1) // 2 != L:
        raise ValueError("cannot infer triangular size from %d" % L)
    n = m + abs(k)
    idx = jnp.tril_indices(n, k) if lower else jnp.triu_indices(n, k)
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., idx[0], idx[1]].set(A)


@register("_linalg_gelqf", num_outputs=2)
def _linalg_gelqf(A):
    """LQ factorization: A = L @ Q with Q orthonormal rows (parity:
    la_op.cc gelqf).  Computed as the transposed QR of A^T."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_potri")
def _linalg_potri(A, lower=True):
    """Inverse from a Cholesky factor: potri(L) = (L L^T)^-1."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype),
                           A.shape)
    linv = jax.scipy.linalg.solve_triangular(A, eye, lower=lower)
    return jnp.swapaxes(linv, -1, -2) @ linv if lower \
        else linv @ jnp.swapaxes(linv, -1, -2)


@register("_linalg_slogdet", num_outputs=2)
def _linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register("_linalg_syevd", num_outputs=2)
def _linalg_syevd(A):
    """Symmetric eigendecomposition; rows of U are eigenvectors
    (A = U^T diag(L) U), matching la_op.cc syevd."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_trmm")
def _linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = (B @ tri) if rightside else (tri @ B)
    return alpha * out
