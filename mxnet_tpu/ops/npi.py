"""NumPy-internal operator names (``_np*``/``_npi_*``/``_npx_*``).

Parity: ``src/operator/numpy/*.cc`` — the reference registers ~150 internal
ops that back ``mx.np``; its frontend dispatches to them via
``mx.nd._internal``.  Here ``mx.np`` lowers through jnp closures directly
(numpy/__init__.py), but the internal *names* are part of the operator
surface (visible in ``mx.nd`` listings, usable from symbols), so this wave
registers them over the same jnp kernels.

Dynamic-output-shape ops (``_npi_unique``, ``_npx_nonzero``,
``_npi_delete``) cannot be fixed-shape XLA computations; they run through
the imperative override hook (host round-trip) exactly like the
reference's dynamic-shape ops force a synchronization
(``src/operator/numpy/np_unique_op.cc``).
"""
from __future__ import annotations

import numpy as _onp
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias, register_invoke_override

# ---------------------------------------------------------------------------
# reductions / shape manipulation (_np_* namespace)
# ---------------------------------------------------------------------------


def _ax(axis):
    return tuple(axis) if isinstance(axis, (tuple, list)) else axis


register("_np_all")(lambda data, axis=None, keepdims=False:
                    jnp.all(data, axis=_ax(axis), keepdims=keepdims))
register("_np_any")(lambda data, axis=None, keepdims=False:
                    jnp.any(data, axis=_ax(axis), keepdims=keepdims))
register("_np_sum")(lambda a, axis=None, dtype=None, keepdims=False:
                    jnp.sum(a, axis=_ax(axis), keepdims=keepdims))
register("_np_max")(lambda a, axis=None, keepdims=False:
                    jnp.max(a, axis=_ax(axis), keepdims=keepdims))
register("_np_min")(lambda a, axis=None, keepdims=False:
                    jnp.min(a, axis=_ax(axis), keepdims=keepdims))
register("_np_prod")(lambda a, axis=None, dtype=None, keepdims=False:
                     jnp.prod(a, axis=_ax(axis), keepdims=keepdims))
register("_npi_mean")(lambda a, axis=None, dtype=None, keepdims=False:
                      jnp.mean(a, axis=_ax(axis), keepdims=keepdims))
register("_npi_std")(lambda a, axis=None, ddof=0, keepdims=False:
                     jnp.std(a, axis=_ax(axis), ddof=ddof,
                             keepdims=keepdims))
register("_npi_var")(lambda a, axis=None, ddof=0, keepdims=False:
                     jnp.var(a, axis=_ax(axis), ddof=ddof,
                             keepdims=keepdims))
register("_np_cumsum")(lambda a, axis=None, dtype=None:
                       jnp.cumsum(a, axis=axis))
register("_np_copy")(lambda a: a + 0)
register("_np_reshape")(lambda a, newshape=(), order="C":
                        jnp.reshape(a, tuple(newshape)))
register("_npx_reshape")(lambda a, newshape=(), reverse=False:
                         jnp.reshape(a, tuple(newshape)))
register("_np_squeeze")(lambda a, axis=None: jnp.squeeze(a, axis=_ax(axis)))
register("_np_transpose")(lambda a, axes=None:
                          jnp.transpose(a, _ax(axes)))
register("_np_moveaxis")(lambda a, source=0, destination=0:
                         jnp.moveaxis(a, _ax(source), _ax(destination)))
register("_np_roll")(lambda a, shift=0, axis=None:
                     jnp.roll(a, _ax(shift) if isinstance(shift, (tuple, list))
                              else shift, axis=_ax(axis)))
register("_npi_rot90")(lambda a, k=1, axes=(0, 1):
                       jnp.rot90(a, k=k, axes=tuple(axes)))
register("_npi_flip")(lambda a, axis=None: jnp.flip(a, axis=_ax(axis)))
register("_np_diag")(lambda a, k=0: jnp.diag(a, k=k))
register("_np_diagflat")(lambda a, k=0: jnp.diagflat(a, k=k))
register("_np_diagonal")(lambda a, offset=0, axis1=0, axis2=1:
                         jnp.diagonal(a, offset=offset, axis1=axis1,
                                      axis2=axis2))
register("_np_trace")(lambda a, offset=0, axis1=0, axis2=1:
                      jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2))
register("_npi_tril")(lambda a, k=0: jnp.tril(a, k=k))
register("_np_dot")(lambda a, b: jnp.dot(a, b))
register("_npi_broadcast_to")(lambda a, shape=():
                              jnp.broadcast_to(a, tuple(shape)))
register("_npi_share_memory")(lambda a, b: jnp.zeros((1,), jnp.bool_))


@register("_np_atleast_1d", num_outputs=-1)
def _np_atleast_1d(*arys):
    return tuple(jnp.atleast_1d(a) for a in arys)


@register("_np_atleast_2d", num_outputs=-1)
def _np_atleast_2d(*arys):
    return tuple(jnp.atleast_2d(a) for a in arys)


@register("_np_atleast_3d", num_outputs=-1)
def _np_atleast_3d(*arys):
    return tuple(jnp.atleast_3d(a) for a in arys)


# ---------------------------------------------------------------------------
# elementwise binary (+ scalar / reflected-scalar variants)
# ---------------------------------------------------------------------------


def _binary(name, jfn):
    register(name)(lambda lhs, rhs: jfn(lhs, rhs))
    register(name + "_scalar")(
        lambda data, scalar=0.0, is_int=False: jfn(
            data, jnp.asarray(scalar, data.dtype)))


def _rbinary(name, jfn):
    register(name)(lambda data, scalar=0.0, is_int=False: jfn(
        jnp.asarray(scalar, data.dtype), data))


_binary("_npi_add", jnp.add)
_binary("_npi_subtract", jnp.subtract)
_rbinary("_npi_rsubtract_scalar", jnp.subtract)
_binary("_npi_multiply", jnp.multiply)
_binary("_npi_mod", lambda a, b: jnp.mod(a, b))
_rbinary("_npi_rmod_scalar", jnp.mod)
_binary("_npi_power", jnp.power)
_rbinary("_npi_rpower_scalar", jnp.power)
_binary("_npi_copysign", jnp.copysign)
_rbinary("_npi_rcopysign_scalar", jnp.copysign)
_binary("_npi_arctan2", jnp.arctan2)
_rbinary("_npi_rarctan2_scalar", jnp.arctan2)
_binary("_npi_lcm", lambda a, b: jnp.lcm(a.astype(jnp.int32),
                                         jnp.asarray(b, jnp.int32)))
_binary("_npi_ldexp", lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)))
_rbinary("_npi_rldexp_scalar", lambda a, b: jnp.ldexp(
    a, b.astype(jnp.int32)))
_binary("_npi_bitwise_or", lambda a, b: jnp.bitwise_or(
    a.astype(jnp.int32), jnp.asarray(b, jnp.int32)))
_binary("_npi_bitwise_xor", lambda a, b: jnp.bitwise_xor(
    a.astype(jnp.int32), jnp.asarray(b, jnp.int32)))
register("_npi_bitwise_not")(lambda data: jnp.bitwise_not(
    data.astype(jnp.int32)))


@register("_npi_true_divide")
def _npi_true_divide(lhs, rhs):
    out = jnp.true_divide(lhs, rhs)
    return out.astype(jnp.float32) if jnp.issubdtype(
        out.dtype, jnp.integer) else out


register("_npi_true_divide_scalar")(
    lambda data, scalar=1.0, is_int=False:
    jnp.true_divide(data, scalar).astype(
        jnp.float32 if jnp.issubdtype(data.dtype, jnp.integer)
        else data.dtype))
register("_npi_rtrue_divide_scalar")(
    lambda data, scalar=1.0, is_int=False:
    jnp.true_divide(jnp.asarray(scalar), data).astype(
        jnp.float32 if jnp.issubdtype(data.dtype, jnp.integer)
        else data.dtype))
register("_npi_hypot")(lambda x1, x2: jnp.hypot(x1, x2))
register("_npi_log")(lambda data: jnp.log(data))
register("_npi_logical_not")(lambda data: jnp.logical_not(data))
register("_npi_deg2rad")(lambda data: jnp.deg2rad(data))
register("_npi_rad2deg")(lambda data: jnp.rad2deg(data))
register("_npi_around")(lambda data, decimals=0:
                        jnp.around(data, decimals=decimals))
register("_npi_nan_to_num", aliases=("_npi_backward_nan_to_num",))(
    lambda data, copy=True, nan=0.0, posinf=None, neginf=None:
    jnp.nan_to_num(data, nan=nan, posinf=posinf, neginf=neginf))
register("_npx_relu")(lambda data: jnp.maximum(data, 0))
register("_npx_sigmoid")(lambda data: jax.nn.sigmoid(data))


@register("_npx_constraint_check")
def _constraint_check(data, msg="constraint violated"):
    # reference raises on violation at wait time; value semantics: all()
    return jnp.all(data)


register("_npi_argmax")(lambda data, axis=None, keepdims=False:
                        jnp.argmax(data, axis=axis, keepdims=keepdims))
register("_npi_argmin")(lambda data, axis=None, keepdims=False:
                        jnp.argmin(data, axis=axis, keepdims=keepdims))


@register("_npi_average", num_outputs=2,
          inputs=("a", "weights"))
def _npi_average(a, weights=None, axis=None, returned=False):
    if weights is None:
        avg = jnp.mean(a, axis=_ax(axis))
        cnt = jnp.asarray(a.size / avg.size, avg.dtype)
        return avg, jnp.broadcast_to(cnt, avg.shape)
    w = weights
    num = jnp.sum(a * w, axis=_ax(axis))
    den = jnp.sum(jnp.broadcast_to(w, a.shape), axis=_ax(axis))
    return num / den, den


def _bincount_override(inputs, attrs, out):
    import numpy as onp

    data = inputs[0].asnumpy().astype(onp.int64).reshape(-1)
    w = inputs[1].asnumpy().reshape(-1) if len(inputs) > 1 else None
    res = onp.bincount(data, weights=w,
                       minlength=int(attrs.get("minlength", 0) or 0))
    return inputs[0]._op_result_cls(jnp.asarray(res))


# output length is max(data)+1 — data-dependent, so host path like unique
register("_npi_bincount")(
    lambda data, weights=None, minlength=0: data)
register_invoke_override("_npi_bincount", _bincount_override)


@register("_npi_diff")
def _npi_diff(a, n=1, axis=-1):
    return jnp.diff(a, n=int(n), axis=axis)


# windows
register("_npi_blackman")(lambda M=1, dtype="float32":
                          jnp.blackman(int(M)).astype(jnp.dtype(dtype)))
register("_npi_hamming")(lambda M=1, dtype="float32":
                         jnp.hamming(int(M)).astype(jnp.dtype(dtype)))
register("_npi_hanning")(lambda M=1, dtype="float32":
                         jnp.hanning(int(M)).astype(jnp.dtype(dtype)))

# creation
register("_npi_zeros")(lambda shape=(), dtype="float32", ctx=None:
                       jnp.zeros(tuple(shape), jnp.dtype(dtype)))
register("_npi_ones")(lambda shape=(), dtype="float32", ctx=None:
                      jnp.ones(tuple(shape), jnp.dtype(dtype)))
register("_npi_identity")(lambda shape=(), dtype="float32", ctx=None:
                          jnp.eye(int(shape[0]) if isinstance(
                              shape, (tuple, list)) else int(shape),
                              dtype=jnp.dtype(dtype)))
register("_npi_eye")(lambda N=1, M=None, k=0, dtype="float32", ctx=None:
                     jnp.eye(int(N), int(M) if M else None, int(k),
                             dtype=jnp.dtype(dtype)))
register("_npi_arange")(
    lambda start=0.0, stop=None, step=1.0, dtype="float32", ctx=None:
    jnp.arange(start, stop, step, dtype=jnp.dtype(dtype)))
register("_npi_logspace")(
    lambda start=0.0, stop=1.0, num=50, endpoint=True, base=10.0,
    dtype="float32", ctx=None:
    jnp.logspace(start, stop, int(num), endpoint, base,
                 dtype=jnp.dtype(dtype)))
register("_npi_indices")(
    lambda dimensions=(), dtype="int32", ctx=None:
    jnp.stack(jnp.meshgrid(*[jnp.arange(d) for d in dimensions],
                           indexing="ij")).astype(jnp.dtype(dtype)))
register("_npi_full_like")(
    lambda a, fill_value=0.0, dtype=None, ctx=None:
    jnp.full_like(a, fill_value,
                  dtype=jnp.dtype(dtype) if dtype else None))

# stacking
register("_npi_concatenate")(
    lambda *data, axis=0, dim=None, num_args=1:
    jnp.concatenate(data, axis=int(dim if dim is not None else axis)))
register("_npi_stack")(lambda *data, axis=0, num_args=1:
                       jnp.stack(data, axis=axis))
register("_npi_vstack")(lambda *data, num_args=1: jnp.vstack(data))
register("_npi_hstack")(lambda *data, num_args=1: jnp.hstack(data))
register("_npi_dstack")(lambda *data, num_args=1: jnp.dstack(data))
register("_npi_column_stack")(lambda *data, num_args=1:
                              jnp.column_stack(data))


@register("_npi_hsplit", num_outputs=-1,
          aliases=("_npi_hsplit_backward",))
def _npi_hsplit(data, indices=None, axis=1, squeeze_axis=False,
                sections=0):
    n = int(sections) if sections else len(indices) + 1
    if sections:
        return tuple(jnp.split(data, int(sections),
                               axis=1 if data.ndim > 1 else 0))
    return tuple(jnp.split(data, list(indices),
                           axis=1 if data.ndim > 1 else 0))


@register("_npi_where")
def _npi_where(condition, x, y):
    return jnp.where(condition.astype(jnp.bool_), x, y)


@register("_npi_boolean_mask_assign_scalar")
def _npi_boolean_mask_assign_scalar(data, mask, value=0.0):
    return jnp.where(mask.astype(jnp.bool_), jnp.asarray(value, data.dtype),
                     data)


@register("_npi_boolean_mask_assign_tensor")
def _npi_boolean_mask_assign_tensor(data, mask, value):
    return jnp.where(mask.astype(jnp.bool_), value, data)


# linalg (_npi namespace; the heavier set lives in tensor.py _linalg_*)
register("_npi_cholesky")(lambda a: jnp.linalg.cholesky(a))
register("_npi_solve")(lambda a, b: jnp.linalg.solve(a, b))
register("_npi_pinv")(lambda a, rcond=None:
                      jnp.linalg.pinv(a, rcond=rcond))
register("_npi_pinv_scalar_rcond")(
    lambda a, rcond=1e-15: jnp.linalg.pinv(a, rcond=float(rcond)))


@register("_npi_svd", num_outputs=3)
def _npi_svd(a):
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return u, s, vt


register("_npi_tensorinv")(lambda a, ind=2: jnp.linalg.tensorinv(a, ind=ind))
register("_npi_tensorsolve")(
    lambda a, b, a_axes=None: jnp.linalg.tensorsolve(a, b))


@register("_npi_tensordot")
def _npi_tensordot(a, b, a_axes_summed=(), b_axes_summed=()):
    return jnp.tensordot(a, b, axes=(tuple(a_axes_summed),
                                     tuple(b_axes_summed)))


register("_npi_tensordot_int_axes")(
    lambda a, b, axes=2: jnp.tensordot(a, b, axes=int(axes)))

# random (_npi namespace; threefry key prepended by the dispatcher)
register("_npi_uniform", needs_rng=True, aliases=("_npi_uniform_n",))(
    lambda key, low=0.0, high=1.0, size=(), ctx=None, dtype="float32":
    jax.random.uniform(key, tuple(size) if size else (),
                       jnp.dtype(dtype), low, high))
register("_npi_normal", needs_rng=True, aliases=("_npi_normal_n",))(
    lambda key, loc=0.0, scale=1.0, size=(), ctx=None, dtype="float32":
    loc + scale * jax.random.normal(key, tuple(size) if size else (),
                                    jnp.dtype(dtype)))
register("_npi_bernoulli", needs_rng=True)(
    lambda key, prob=0.5, logit=None, size=(), ctx=None, dtype="float32",
    is_logit=False:
    jax.random.bernoulli(
        key, jax.nn.sigmoid(jnp.asarray(logit)) if is_logit else prob,
        tuple(size) if size else ()).astype(jnp.dtype(dtype)))
register("_npi_exponential", needs_rng=True)(
    lambda key, scale=1.0, size=(), ctx=None:
    scale * jax.random.exponential(key, tuple(size) if size else ()))
register("_npi_gamma", needs_rng=True)(
    lambda key, shape=1.0, scale=1.0, size=(), ctx=None, dtype="float32":
    scale * jax.random.gamma(key, shape, tuple(size) if size else (),
                             jnp.dtype(dtype)))
@register("_npi_choice", needs_rng=True, inputs=("input1", "input2"))
def _npi_choice(key, input1=None, input2=None, a=None, size=(),
                replace=True, ctx=None):
    """np.random.choice backend op: the pool is either the int attr ``a``
    or a 1-D array input; optional probability weights are the next array
    input.  Like the reference (numpy/random/np_choice_op.h
    NumpyChoiceOpType) the op always returns int64 INDICES into the pool;
    callers wanting values gather ``pool[indices]`` themselves (the
    ``mx.np.random.choice`` frontend samples values directly and does not
    route through this op)."""
    if a is not None:
        n_pool, p = int(a), input1
    else:
        n_pool, p = int(input1.shape[0]), input2
    if p is not None:
        p = p / jnp.sum(p)
    return jax.random.choice(key, n_pool, tuple(size) if size else (),
                             replace=bool(replace), p=p).astype(jnp.int64)
@register("_npi_multinomial", needs_rng=True, inputs=("data",))
def _npi_multinomial(key, data, n=1, pvals=None, size=(), ctx=None):
    """np.random.multinomial semantics: ``n`` draws per experiment,
    returning per-category counts of shape size + (k,)."""
    k = data.shape[-1]
    out_shape = tuple(size) if size else ()
    draws = jax.random.categorical(
        key, jnp.log(jnp.maximum(data, 1e-30)),
        shape=(int(n),) + out_shape)
    return jax.nn.one_hot(draws, k, dtype=jnp.int64).sum(axis=0)
register("_sample_poisson", needs_rng=True)(
    lambda key, lam, shape=(): jax.random.poisson(
        key, lam, shape=tuple(shape) + lam.shape if shape
        else lam.shape).astype(jnp.float32))
register("_sample_exponential", needs_rng=True)(
    lambda key, lam, shape=(): (1.0 / lam) * jax.random.exponential(
        key, tuple(shape) + lam.shape if shape else lam.shape))


@register("_sample_negative_binomial", needs_rng=True)
def _sample_negative_binomial(key, k, p, shape=()):
    out_shape = (tuple(shape) + k.shape) if shape else k.shape
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, jnp.broadcast_to(k, out_shape)) \
        * (1 - p) / jnp.maximum(p, 1e-12)
    return jax.random.poisson(k2, lam).astype(jnp.float32)


@register("_sample_generalized_negative_binomial", needs_rng=True)
def _sample_gnb(key, mu, alpha, shape=()):
    out_shape = (tuple(shape) + mu.shape) if shape else mu.shape
    k1, k2 = jax.random.split(key)
    a = 1.0 / jnp.maximum(alpha, 1e-12)
    lam = jax.random.gamma(k1, jnp.broadcast_to(a, out_shape)) \
        * jnp.broadcast_to(mu, out_shape) / a
    return jax.random.poisson(k2, lam).astype(jnp.float32)


# ---------------------------------------------------------------------------
# dynamic-output-shape ops: imperative override (host round-trip), like the
# reference's dynamic-shape ops (np_unique_op.cc syncs to CPU too)
# ---------------------------------------------------------------------------


def _unique_override(inputs, attrs, out):
    import numpy as onp
    from ..ndarray.ndarray import NDArray

    data = inputs[0].asnumpy()
    ret = onp.unique(
        data,
        return_index=bool(attrs.get("return_index", False)),
        return_inverse=bool(attrs.get("return_inverse", False)),
        return_counts=bool(attrs.get("return_counts", False)),
        axis=attrs.get("axis", None))
    cls = inputs[0]._op_result_cls
    if isinstance(ret, tuple):
        return [cls(jnp.asarray(r)) for r in ret]
    return cls(jnp.asarray(ret))


def _nonzero_override(inputs, attrs, out):
    import numpy as onp

    data = inputs[0].asnumpy()
    idx = onp.stack(onp.nonzero(data), axis=-1).astype(onp.int64)
    return inputs[0]._op_result_cls(jnp.asarray(idx))


def _delete_override(inputs, attrs, out):
    import numpy as onp

    data = inputs[0].asnumpy()
    if len(inputs) > 1:
        obj = inputs[1].asnumpy().astype(onp.int64)
    else:
        start = attrs.get("start", None)
        if start is not None:
            obj = slice(int(start), int(attrs.get("stop", 0)),
                        int(attrs.get("step", 1)))
        else:
            obj = int(attrs.get("int_ind", 0))
    res = onp.delete(data, obj, axis=attrs.get("axis", None))
    return inputs[0]._op_result_cls(jnp.asarray(res))


register("_npi_unique")(lambda data, return_index=False,
                        return_inverse=False, return_counts=False,
                        axis=None: data)
register("_npx_nonzero")(lambda data: data)
register("_npi_delete")(lambda data, obj=None, start=None, stop=None,
                        step=None, int_ind=None, axis=None: data)
register_invoke_override("_npi_unique", _unique_override)
register_invoke_override("_npx_nonzero", _nonzero_override)
register_invoke_override("_npi_delete", _delete_override)


# ---------------------------------------------------------------------------
# statistics wave (reference: python/mxnet/numpy/multiarray.py percentile/
# quantile/histogram + src/operator/numpy/np_percentile_op.cc etc.)
# ---------------------------------------------------------------------------

def _as_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@register("_npi_percentile")
def _npi_percentile(a, q=50.0, axis=None, interpolation="linear",
                    keepdims=False):
    qv = jnp.asarray(q, jnp.float32)
    return jnp.percentile(a.astype(jnp.float32), qv, axis=_as_axis(axis),
                          method=str(interpolation),
                          keepdims=bool(keepdims))


@register("_npi_quantile")
def _npi_quantile(a, q=0.5, axis=None, interpolation="linear",
                  keepdims=False):
    qv = jnp.asarray(q, jnp.float32)
    return jnp.quantile(a.astype(jnp.float32), qv, axis=_as_axis(axis),
                        method=str(interpolation), keepdims=bool(keepdims))


@register("_npi_median")
def _npi_median(a, axis=None, keepdims=False):
    return jnp.median(a.astype(jnp.float32), axis=_as_axis(axis),
                      keepdims=bool(keepdims))


@register("_npi_histogram", num_outputs=2)
def _npi_histogram(data, bin_cnt=10, range=None):
    lo, hi = (float(range[0]), float(range[1])) if range is not None \
        else (None, None)
    if lo is None:
        # dynamic range still jit-safe: min/max are reductions
        lo_v = jnp.min(data).astype(jnp.float32)
        hi_v = jnp.max(data).astype(jnp.float32)
    else:
        lo_v, hi_v = jnp.float32(lo), jnp.float32(hi)
    counts, edges = jnp.histogram(
        data.astype(jnp.float32), bins=int(bin_cnt), range=(lo_v, hi_v))
    return counts.astype(jnp.int64), edges


@register("_npi_cov")
def _npi_cov(m, rowvar=True, bias=False, ddof=None):
    return jnp.cov(m.astype(jnp.float32), rowvar=bool(rowvar),
                   bias=bool(bias),
                   ddof=None if ddof is None else int(ddof))


@register("_npi_corrcoef")
def _npi_corrcoef(x, rowvar=True):
    return jnp.corrcoef(x.astype(jnp.float32), rowvar=bool(rowvar))


@register("_npi_ptp")
def _npi_ptp(a, axis=None, keepdims=False):
    return jnp.ptp(a, axis=_as_axis(axis), keepdims=bool(keepdims))


for _name, _jfn in [("nanmean", jnp.nanmean), ("nanstd", jnp.nanstd),
                    ("nanvar", jnp.nanvar)]:
    def _mk_nan(jfn):
        def f(a, axis=None, ddof=0, keepdims=False):
            kw = {"axis": _as_axis(axis), "keepdims": bool(keepdims)}
            if jfn is not jnp.nanmean:
                kw["ddof"] = int(ddof)
            return jfn(a.astype(jnp.float32), **kw)
        return f
    register("_npi_" + _name)(_mk_nan(_jfn))

for _name, _jfn in [("nanmax", jnp.nanmax), ("nanmin", jnp.nanmin),
                    ("nansum", jnp.nansum), ("nanprod", jnp.nanprod)]:
    def _mk_nan2(jfn):
        def f(a, axis=None, keepdims=False):
            return jfn(a, axis=_as_axis(axis), keepdims=bool(keepdims))
        return f
    register("_npi_" + _name)(_mk_nan2(_jfn))

register("_npi_nanargmax")(lambda a, axis=None: jnp.nanargmax(
    a, axis=None if axis is None else int(axis)))
register("_npi_nanargmin")(lambda a, axis=None: jnp.nanargmin(
    a, axis=None if axis is None else int(axis)))


# ---------------------------------------------------------------------------
# window functions (reference: src/operator/numpy/np_window_op.cc)
# ---------------------------------------------------------------------------

register("_npi_bartlett")(lambda M=10, ctx=None, dtype="float32":
                          jnp.bartlett(int(M)).astype(jnp.dtype(dtype)))


# ---------------------------------------------------------------------------
# misc numpy wave
# ---------------------------------------------------------------------------

@register("_npi_polyval", inputs=("p", "x"))
def _npi_polyval(p, x):
    return jnp.polyval(p.astype(jnp.float32), x.astype(jnp.float32))


@register("_npi_ediff1d", inputs=("data", "to_end", "to_begin"))
def _npi_ediff1d(data, to_end=None, to_begin=None):
    return jnp.ediff1d(data, to_end=to_end, to_begin=to_begin)


@register("_npi_digitize", inputs=("x", "bins"))
def _npi_digitize(x, bins, right=False):
    return jnp.digitize(x, bins, right=bool(right)).astype(jnp.int64)


@register("_npi_trapz", inputs=("y", "x"))
def _npi_trapz(y, x=None, dx=1.0, axis=-1):
    if x is None:
        return jnp.trapezoid(y.astype(jnp.float32), dx=float(dx),
                             axis=int(axis))
    return jnp.trapezoid(y.astype(jnp.float32),
                         x.astype(jnp.float32), axis=int(axis))


@register("_npi_cross", inputs=("a", "b"))
def _npi_cross(a, b, axisa=-1, axisb=-1, axisc=-1, axis=None):
    if axis is not None:
        axisa = axisb = axisc = int(axis)
    return jnp.cross(a, b, axisa=int(axisa), axisb=int(axisb),
                     axisc=int(axisc))


for _name in ("fmod", "heaviside", "logaddexp", "nextafter"):
    def _mk_bin(jfn):
        def f(a, b):
            return jfn(a, b)
        return f
    register("_npi_" + _name, inputs=("a", "b"))(
        _mk_bin(getattr(jnp, _name)))

register("_npi_gcd", inputs=("a", "b"))(
    lambda a, b: jnp.gcd(a.astype(jnp.int32),
                         jnp.asarray(b).astype(jnp.int32)))

for _name in ("signbit", "spacing", "cbrt", "positive", "fabs"):
    if not hasattr(jnp, _name):
        continue
    def _mk_un(jfn):
        def f(a):
            return jfn(a)
        return f
    register("_npi_" + _name)(_mk_un(getattr(jnp, _name)))


# ---------------------------------------------------------------------------
# set ops: output shapes are data-dependent -> host path (same stance as
# _npi_unique above; reference computes these on CPU too)
# ---------------------------------------------------------------------------

def _set_op_override(onp_fn, n_in=2, takes_assume_unique=True):
    def handler(inputs, attrs, out):
        args = [x.asnumpy() for x in inputs[:n_in] if x is not None]
        kwargs = {}
        if takes_assume_unique and attrs.get("assume_unique"):
            kwargs["assume_unique"] = True
        res = onp_fn(*args, **kwargs)
        return inputs[0]._op_result_cls(jnp.asarray(res))
    return handler


import numpy as _host_np  # noqa: E402

for _name, _fn, _au in [("intersect1d", _host_np.intersect1d, True),
                        ("union1d", _host_np.union1d, False),
                        ("setdiff1d", _host_np.setdiff1d, True),
                        ("setxor1d", _host_np.setxor1d, True)]:
    register("_npi_" + _name, inputs=("a", "b"))(
        lambda a, b, assume_unique=False: a)
    register_invoke_override(
        "_npi_" + _name,
        _set_op_override(_fn, takes_assume_unique=_au))


@register("_npi_isin", inputs=("element", "test_elements"))
def _npi_isin(element, test_elements, assume_unique=False, invert=False):
    # static output shape (same as element) -> jit-safe
    return jnp.isin(element, test_elements, invert=bool(invert))
