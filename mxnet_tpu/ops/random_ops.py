"""Random sampling operators.

Reference: ``src/operator/random/sample_op.cc`` (uniform/normal/gamma/...),
``multisample_op.cc``, ``shuffle_op.cc``, ``pdf_op.cc``.  TPU-native: every op
takes a threefry key (threaded in by the dispatcher, see registry.needs_rng) —
stateless, reproducible, and splittable across a device mesh without the
per-GPU generator state of the reference (``src/resource.cc`` kRandom).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register("_random_uniform", aliases=("uniform", "random_uniform"), needs_rng=True)
def _random_uniform(key, low=0.0, high=1.0, shape=(), dtype="float32"):
    return jax.random.uniform(key, _shape(shape), jnp.dtype(dtype), low, high)


@register("_random_normal", aliases=("normal", "random_normal"), needs_rng=True)
def _random_normal(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.normal(key, _shape(shape), jnp.dtype(dtype))


@register("_random_gamma", aliases=("gamma_sample", "random_gamma"), needs_rng=True)
def _random_gamma(key, alpha=1.0, beta=1.0, shape=(), dtype="float32"):
    return beta * jax.random.gamma(key, alpha, _shape(shape), jnp.dtype(dtype))


@register("_random_exponential", aliases=("random_exponential",), needs_rng=True)
def _random_exponential(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.exponential(key, _shape(shape), jnp.dtype(dtype)) / lam


@register("_random_poisson", aliases=("random_poisson",), needs_rng=True)
def _random_poisson(key, lam=1.0, shape=(), dtype="float32"):
    return jax.random.poisson(key, lam, _shape(shape)).astype(jnp.dtype(dtype))


@register("_random_negative_binomial", needs_rng=True)
def _random_negative_binomial(key, k=1, p=1.0, shape=(), dtype="float32"):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, _shape(shape)) * (1.0 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(jnp.dtype(dtype))


@register("_random_randint", aliases=("random_randint",), needs_rng=True)
def _random_randint(key, low=0, high=1, shape=(), dtype="int32"):
    return jax.random.randint(key, _shape(shape), low, high, jnp.dtype(dtype))


@register("_random_bernoulli", aliases=("bernoulli",), needs_rng=True)
def _random_bernoulli(key, prob=0.5, shape=(), dtype="float32"):
    return jax.random.bernoulli(key, prob, _shape(shape)).astype(jnp.dtype(dtype))


# sample_* variants: per-element distribution parameters given as input arrays
# (reference multisample_op.cc)


@register("_sample_uniform", aliases=("sample_uniform",), needs_rng=True)
def _sample_uniform(key, low, high, shape=(), dtype="float32"):
    s = _shape(shape)
    out_shape = low.shape + s
    u = jax.random.uniform(key, out_shape, jnp.dtype(dtype))
    low_b = low.reshape(low.shape + (1,) * len(s)).astype(jnp.dtype(dtype))
    high_b = high.reshape(high.shape + (1,) * len(s)).astype(jnp.dtype(dtype))
    return low_b + u * (high_b - low_b)


@register("_sample_normal", aliases=("sample_normal",), needs_rng=True)
def _sample_normal(key, mu, sigma, shape=(), dtype="float32"):
    s = _shape(shape)
    out_shape = mu.shape + s
    z = jax.random.normal(key, out_shape, jnp.dtype(dtype))
    mu_b = mu.reshape(mu.shape + (1,) * len(s)).astype(jnp.dtype(dtype))
    sg_b = sigma.reshape(sigma.shape + (1,) * len(s)).astype(jnp.dtype(dtype))
    return mu_b + z * sg_b


@register("_sample_gamma", aliases=("sample_gamma",), needs_rng=True)
def _sample_gamma(key, alpha, beta, shape=(), dtype="float32"):
    s = _shape(shape)
    out_shape = alpha.shape + s
    a_b = alpha.reshape(alpha.shape + (1,) * len(s)).astype(jnp.dtype(dtype))
    b_b = beta.reshape(beta.shape + (1,) * len(s)).astype(jnp.dtype(dtype))
    return jax.random.gamma(key, a_b, out_shape, jnp.dtype(dtype)) * b_b


@register("_sample_multinomial", aliases=("sample_multinomial",), needs_rng=True)
def _sample_multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    """data: (..., K) probabilities; sample indices (parity: sample_multinomial_op.h)."""
    s = _shape(shape)
    n = 1
    for d in s:
        n *= d
    logits = jnp.log(jnp.maximum(data, 1e-30))
    flat = logits.reshape(-1, logits.shape[-1])
    samp = jax.random.categorical(key, flat[:, None, :], axis=-1,
                                  shape=(flat.shape[0], max(n, 1)))
    out = samp.reshape(data.shape[:-1] + (s if s else ()))
    return out.astype(jnp.dtype(dtype))


@register("_shuffle", aliases=("shuffle",), needs_rng=True)
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("_random_gumbel", needs_rng=True)
def _random_gumbel(key, loc=0.0, scale=1.0, shape=(), dtype="float32"):
    return loc + scale * jax.random.gumbel(key, _shape(shape), jnp.dtype(dtype))


# ----------------------------------------------------------------------------
# _random_pdf_* family: density of *sample* under per-element distribution
# parameters (reference src/operator/random/pdf_op.cc:33-37, functors in
# pdf_op.h).  Parameters have the leftmost subshape of ``sample`` and
# broadcast over the trailing sample dims; ``is_log`` selects log-density.
# TPU-native: the forward is plain differentiable jnp (gradients wrt sample
# AND parameters come from the tape's vjp — no hand-written _backward_pdf_*
# kernels), fused by XLA into one elementwise program.
# ----------------------------------------------------------------------------


def _pdf_bcast(param, sample_ndim):
    """Align a leftmost-subshape parameter to the sample rank."""
    return param.reshape(param.shape + (1,) * (sample_ndim - param.ndim))


def _pdf_out(lpdf, is_log):
    return lpdf if is_log else jnp.exp(lpdf)


@register("_random_pdf_uniform", aliases=("random_pdf_uniform",))
def _random_pdf_uniform(sample, low, high, is_log=False):
    l = _pdf_bcast(low, sample.ndim)
    h = _pdf_bcast(high, sample.ndim)
    lpdf = jnp.broadcast_to(-jnp.log(h - l), sample.shape)
    return _pdf_out(lpdf, is_log)


@register("_random_pdf_normal", aliases=("random_pdf_normal",))
def _random_pdf_normal(sample, mu, sigma, is_log=False):
    u = _pdf_bcast(mu, sample.ndim)
    s = _pdf_bcast(sigma, sample.ndim)
    lpdf = (-0.5 * jnp.square(sample - u) / jnp.square(s)
            - jnp.log(jnp.sqrt(2.0 * jnp.pi) * s))
    return _pdf_out(lpdf, is_log)


@register("_random_pdf_gamma", aliases=("random_pdf_gamma",))
def _random_pdf_gamma(sample, alpha, beta, is_log=False):
    from jax.scipy.special import gammaln

    a = _pdf_bcast(alpha, sample.ndim)
    b = _pdf_bcast(beta, sample.ndim)
    lpdf = (a * jnp.log(b) + (a - 1.0) * jnp.log(sample) - b * sample
            - gammaln(a))
    return _pdf_out(lpdf, is_log)


@register("_random_pdf_exponential", aliases=("random_pdf_exponential",))
def _random_pdf_exponential(sample, lam, is_log=False):
    l = _pdf_bcast(lam, sample.ndim)
    lpdf = jnp.log(l) - l * sample
    return _pdf_out(lpdf, is_log)


@register("_random_pdf_poisson", aliases=("random_pdf_poisson",))
def _random_pdf_poisson(sample, lam, is_log=False):
    from jax.scipy.special import gammaln

    l = _pdf_bcast(lam, sample.ndim)
    lpdf = sample * jnp.log(l) - gammaln(sample + 1.0) - l
    return _pdf_out(lpdf, is_log)


def _nb_lpdf(limit, prob, x):
    """log NB(x; limit, prob) with prob the FAILURE probability
    (pdf_op.h PDF_NegativeBinomial::LPDF)."""
    from jax.scipy.special import gammaln

    return (gammaln(x + limit) - gammaln(x + 1.0) - gammaln(limit)
            + limit * jnp.log(prob) + x * jnp.log(1.0 - prob))


@register("_random_pdf_negative_binomial",
          aliases=("random_pdf_negative_binomial",))
def _random_pdf_negative_binomial(sample, k, p, is_log=False):
    limit = _pdf_bcast(k, sample.ndim)
    prob = _pdf_bcast(p, sample.ndim)
    return _pdf_out(_nb_lpdf(limit, prob, sample), is_log)


@register("_random_pdf_generalized_negative_binomial",
          aliases=("random_pdf_generalized_negative_binomial",))
def _random_pdf_generalized_negative_binomial(sample, mu, alpha, is_log=False):
    m = _pdf_bcast(mu, sample.ndim)
    a = _pdf_bcast(alpha, sample.ndim)
    limit = 1.0 / a
    prob = 1.0 / (m * a + 1.0)
    return _pdf_out(_nb_lpdf(limit, prob, sample), is_log)


@register("_random_pdf_dirichlet", aliases=("random_pdf_dirichlet",))
def _random_pdf_dirichlet(sample, alpha, is_log=False):
    """alpha: (s..., k); sample: (s..., m..., k); out: (s..., m...)."""
    from jax.scipy.special import gammaln

    a = alpha.reshape(alpha.shape[:-1]
                      + (1,) * (sample.ndim - alpha.ndim)
                      + alpha.shape[-1:])
    lpdf = (jnp.sum((a - 1.0) * jnp.log(sample), axis=-1)
            + gammaln(jnp.sum(a, axis=-1))
            - jnp.sum(gammaln(a), axis=-1))
    return _pdf_out(lpdf, is_log)
