"""Misc / legacy operator wave: loss layers, im2col, LRN, histogram,
image ops, spatial transformer, adaptive pooling.

Parity targets (all under /root/reference/src/operator/):
``regression_output{-inl.h,.cc}``, ``svm_output{-inl.h,.cc}``,
``nn/im2col.h``, ``nn/lrn.cc``, ``tensor/histogram.cc``,
``image/image_random.cc``, ``image/resize.cc``, ``image/crop.cc``,
``spatial_transformer.cc``, ``grid_generator.cc``, ``correlation.cc``,
``contrib/adaptive_avg_pooling.cc``, ``contrib/bilinear_resize.cc``,
``tensor/square_sum{-inl.h,.cc}``, ``tensor/matrix_op.cc`` slice-assign,
``tensor/indexing_op.cc`` batch_take / ravel ops, ``quadratic_op.cc``,
``contrib/stes_op.cc`` (straight-through estimators), ``make_loss.cc``.

TPU-native notes: loss-layer ops whose reference backward ignores the
incoming gradient are built on ``jax.custom_vjp``; im2col uses XLA's
``conv_general_dilated_patches`` (MXU-friendly); col2im scatter-adds with
static python loops over the (small, static) kernel window so XLA sees a
fixed fusion graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ----------------------------------------------------------------------------
# simple elementwise / reduction additions
# ----------------------------------------------------------------------------

register("add_n", aliases=("ElementWiseSum", "_sum"), num_outputs=1)(
    lambda *arrays, num_args=1: sum(arrays[1:], arrays[0])
)


@register("hard_sigmoid")
def _hard_sigmoid(data, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("moments", num_outputs=2)
def _moments(data, axes=None, keepdims=False):
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=keepdims)
    var = jnp.mean(jnp.square(data - jnp.mean(data, axis=ax, keepdims=True)),
                   axis=ax, keepdims=keepdims)
    return mean, var


@register("_square_sum")
def _square_sum(data, axis=None, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else axis
    return jnp.sum(jnp.square(data), axis=ax, keepdims=keepdims)


@register("_grad_add")
def _grad_add(lhs, rhs):
    return lhs + rhs


@register("_hypot_scalar")
def _hypot_scalar(data, scalar=0.0):
    return jnp.hypot(data, jnp.asarray(scalar, data.dtype))


@register("_zeros_without_dtype")
def _zeros_without_dtype(shape=(), ctx=None):
    return jnp.zeros(shape, jnp.float32)


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    return lhs


@register("_rnn_param_concat")
def _rnn_param_concat(*arrays, dim=0, num_args=1):
    return jnp.concatenate([a.reshape(-1) if a.ndim != 1 else a
                            for a in arrays], axis=0) if dim == 0 and \
        any(a.ndim != arrays[0].ndim for a in arrays) else \
        jnp.concatenate(arrays, axis=dim)


@register("batch_take")
def _batch_take(a, indices):
    flat = a.reshape(a.shape[0], -1)
    return jnp.take_along_axis(
        flat, indices.reshape(-1, 1).astype(jnp.int32), axis=1).reshape(
            indices.shape)


@register("_unravel_index")
def _unravel_index(data, shape=()):
    coords = jnp.unravel_index(data.astype(jnp.int32).reshape(-1),
                               tuple(shape))
    return jnp.stack(coords, axis=0).reshape((len(shape),) + data.shape)


@register("_ravel_multi_index")
def _ravel_multi_index(data, shape=()):
    idx = tuple(data[i].astype(jnp.int32) for i in range(len(shape)))
    return jnp.ravel_multi_index(idx, tuple(shape), mode="clip").astype(
        data.dtype)


@register("_histogram", num_outputs=2)
def _histogram(data, bins=None, bin_cnt=None, range=None):
    if bins is not None and getattr(bins, "ndim", 0) > 0:
        edges = bins
        cnt = jnp.histogram(data.reshape(-1), bins=edges)[0]
        return cnt, edges
    lo, hi = (range if range is not None else (0.0, 1.0))
    cnt, edges = jnp.histogram(data.reshape(-1), bins=int(bin_cnt or 10),
                               range=(lo, hi))
    return cnt, edges


@register("_sparse_retain")
def _sparse_retain_op(data, indices):
    """Keep only the listed rows (others zeroed) — dense rendering of the
    row_sparse retain (reference: tensor/sparse_retain.cc)."""
    mask = jnp.zeros((data.shape[0],), data.dtype).at[
        indices.astype(jnp.int32)].set(1)
    return data * mask.reshape((-1,) + (1,) * (data.ndim - 1))


@register("cast_storage")
def _cast_storage(data, stype="default"):
    # dense XLA buffers back every storage type; sparse views are built at
    # the NDArray layer (ndarray/sparse.py), so this is identity on data
    return data


@register("_scatter_plus_scalar")
def _scatter_plus_scalar(data, scalar=0.0):
    return data + jnp.asarray(scalar, data.dtype)


@register("_scatter_minus_scalar")
def _scatter_minus_scalar(data, scalar=0.0):
    return data - jnp.asarray(scalar, data.dtype)


@register("_scatter_elemwise_div")
def _scatter_elemwise_div(lhs, rhs):
    return lhs / rhs


@register("_slice_assign")
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    idx = tuple(
        slice(b if b is not None else None,
              e if e is not None else None,
              (s if s not in (None, 0) else None))
        for b, e, s in zip(begin, end,
                           step if step else (None,) * len(begin)))
    return lhs.at[idx].set(rhs)


@register("_slice_assign_scalar")
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    idx = tuple(
        slice(b if b is not None else None,
              e if e is not None else None,
              (s if s not in (None, 0) else None))
        for b, e, s in zip(begin, end,
                           step if step else (None,) * len(begin)))
    return data.at[idx].set(jnp.asarray(scalar, data.dtype))


alias("_split_v2", "split_v2")
alias("MakeLoss_grad_stop", "stop_gradient")

# ----------------------------------------------------------------------------
# loss-layer ops: reference backward IGNORES the incoming gradient, so these
# are custom_vjp functions, not plain forwards
# ----------------------------------------------------------------------------


def _loss_layer(name, fwd_fn, grad_fn):
    """Build a (data, label) -> out op whose data-grad is grad_fn(out,
    label) * grad_scale / num_output, independent of the cotangent."""

    @jax.custom_vjp
    def f(data, label, grad_scale):
        return fwd_fn(data)

    def f_fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label, grad_scale)

    def f_bwd(res, g):
        out, label, grad_scale = res
        num_output = label.size // label.shape[0] if label.ndim > 0 else 1
        lab = label.reshape(out.shape) if label.size == out.size else label
        return (grad_fn(out, lab) * (grad_scale / num_output),
                jnp.zeros_like(label), jnp.zeros_like(grad_scale))

    f.defvjp(f_fwd, f_bwd)

    @register(name, aliases=(name.lower().replace("output", "_output"),))
    def op(data, label, grad_scale=1.0):
        return f(data, label, jnp.asarray(grad_scale, data.dtype))

    return op


_loss_layer("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
_loss_layer("MAERegressionOutput", lambda d: d, lambda o, l: jnp.sign(o - l))
_loss_layer("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l)


@register("SVMOutput", aliases=("svm_output",))
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False):
    """Forward identity; backward is the (L1|L2) SVM margin gradient
    (parity: svm_output.cc L1_SVM/L2_SVM kernels)."""

    @jax.custom_vjp
    def f(d, lab):
        return d

    def f_fwd(d, lab):
        return d, (d, lab)

    def f_bwd(res, g):
        d, lab = res
        x = d.reshape(d.shape[0], -1)
        k = jax.nn.one_hot(lab.reshape(-1).astype(jnp.int32), x.shape[1],
                           dtype=x.dtype)
        if use_linear:  # L1-SVM
            at_k = -(margin > x).astype(x.dtype)
            off_k = (margin > -x).astype(x.dtype)
        else:  # L2-SVM
            at_k = jnp.where(margin > x, -2.0 * (margin - x), 0.0)
            off_k = jnp.where(margin > -x, 2.0 * (margin + x), 0.0)
        grad = jnp.where(k > 0, at_k, off_k) * regularization_coefficient
        return grad.reshape(d.shape), jnp.zeros_like(lab)

    f.defvjp(f_fwd, f_bwd)
    return f(data, label)


@register("MakeLoss")
def _make_loss_op(data, grad_scale=1.0, valid_thresh=0.0,
                  normalization="null"):
    """Terminal loss marker: forward identity, backward a constant
    grad_scale field (reference: make_loss.cc ignores the head grad)."""

    @jax.custom_vjp
    def f(d):
        return d

    def f_fwd(d):
        return d, (d,)

    def f_bwd(res, g):
        (d,) = res
        scale = grad_scale
        if normalization == "batch":
            scale = scale / d.shape[0]
        elif normalization == "valid":
            n_valid = jnp.maximum(jnp.sum(d > valid_thresh), 1)
            return ((jnp.full_like(d, grad_scale) / n_valid),)
        return (jnp.full_like(d, scale),)

    f.defvjp(f_fwd, f_bwd)
    return f(data)


@register("IdentityAttachKLSparseReg")
def _identity_kl_sparse(data, sparseness_target=0.1, penalty=0.001,
                        momentum=0.9):
    """Identity whose backward adds the KL-sparseness penalty gradient
    (reference: identity_attach_KL_sparse_reg-inl.h; the moving-average
    aux state collapses into the batch estimate under jit)."""

    @jax.custom_vjp
    def f(d):
        return d

    def f_fwd(d):
        return d, (d,)

    def f_bwd(res, g):
        (d,) = res
        rho_hat = jnp.clip(jnp.mean(d, axis=0, keepdims=True), 1e-6,
                           1 - 1e-6)
        kl_grad = penalty * (-sparseness_target / rho_hat
                             + (1 - sparseness_target) / (1 - rho_hat))
        return (g + kl_grad,)

    f.defvjp(f_fwd, f_bwd)
    return f(data)


# ----------------------------------------------------------------------------
# straight-through estimators + quadratic (contrib)
# ----------------------------------------------------------------------------

register("_contrib_round_ste")(
    lambda data: data + lax.stop_gradient(jnp.round(data) - data))
register("_contrib_sign_ste")(
    lambda data: data + lax.stop_gradient(jnp.sign(data) - data))


@register("_contrib_quadratic", aliases=("_contrib_backward_quadratic",))
def _quadratic(data, a=0.0, b=0.0, c=0.0):
    return a * jnp.square(data) + b * data + c


@register("_contrib_allclose")
def _allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.asarray(
        jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        jnp.float32).reshape((1,))


# ----------------------------------------------------------------------------
# im2col / col2im (reference: src/operator/nn/im2col.h)
# ----------------------------------------------------------------------------


def _conv_tuple(v, n):
    if v is None:
        return (1,) * n
    t = tuple(int(x) for x in (v if isinstance(v, (tuple, list)) else (v,)))
    return t * n if len(t) == 1 and n > 1 else t


@register("im2col")
def _im2col(data, kernel=(), stride=(), dilate=(), pad=()):
    nd = len(kernel)
    k = _conv_tuple(kernel, nd)
    s = _conv_tuple(stride or (1,) * nd, nd)
    d = _conv_tuple(dilate or (1,) * nd, nd)
    p = _conv_tuple(pad or (0,) * nd, nd)
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=k, window_strides=s,
        padding=[(pi, pi) for pi in p], rhs_dilation=d)
    # (N, C*prod(k), *out_spatial) -> (N, C*prod(k), L)
    return patches.reshape(patches.shape[0], patches.shape[1], -1)


@register("col2im")
def _col2im(data, output_size=(), kernel=(), stride=(), dilate=(), pad=()):
    """N-D col2im (1D/2D/3D like the reference's im2col_nd_core,
    src/operator/nn/im2col.h:150): scatter-add each kernel tap's column
    back onto its strided output window."""
    import itertools
    import math

    ndim = len(kernel)
    k = _conv_tuple(kernel, ndim)
    s = _conv_tuple(stride or (1,) * ndim, ndim)
    d = _conv_tuple(dilate or (1,) * ndim, ndim)
    p = _conv_tuple(pad or (0,) * ndim, ndim)
    out_sp = tuple(int(x) for x in output_size)
    n = data.shape[0]
    c = data.shape[1] // math.prod(k)
    o = tuple((out_sp[i] + 2 * p[i] - d[i] * (k[i] - 1) - 1) // s[i] + 1
              for i in range(ndim))
    cols = data.reshape((n, c) + tuple(k) + o)
    out = jnp.zeros(
        (n, c) + tuple(out_sp[i] + 2 * p[i] for i in range(ndim)),
        data.dtype)
    for taps in itertools.product(*(range(ki) for ki in k)):
        dst = (slice(None), slice(None)) + tuple(
            slice(taps[i] * d[i], taps[i] * d[i] + o[i] * s[i], s[i])
            for i in range(ndim))
        src = (slice(None), slice(None)) + taps
        out = out.at[dst].add(cols[src])
    unpad = (slice(None), slice(None)) + tuple(
        slice(p[i], p[i] + out_sp[i]) for i in range(ndim))
    return out[unpad]


# ----------------------------------------------------------------------------
# LRN (reference: src/operator/nn/lrn.cc — two outputs: out, tmp_norm)
# ----------------------------------------------------------------------------

@register("LRN", aliases=("lrn",), num_outputs=2)
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    half = int(nsize) // 2
    sq = jnp.square(data)
    window_sum = lax.reduce_window(
        sq, 0.0, lax.add, (1, int(nsize), 1, 1), (1, 1, 1, 1),
        [(0, 0), (half, half), (0, 0), (0, 0)])
    tmp_norm = knorm + (alpha / nsize) * window_sum
    return data * jnp.power(tmp_norm, -beta), tmp_norm


@register("Crop", aliases=("crop_legacy",))
def _crop_op(*arrays, num_args=1, offset=(0, 0), h_w=(0, 0),
             center_crop=False):
    """Legacy Crop (reference: src/operator/crop.cc): crop input 0 spatially
    to ``h_w`` or to the size of a second 'like' input."""
    data = arrays[0]
    if len(arrays) > 1:
        th, tw = arrays[1].shape[2], arrays[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
    if center_crop:
        oy = (data.shape[2] - th) // 2
        ox = (data.shape[3] - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return data[:, :, oy:oy + th, ox:ox + tw]


# ----------------------------------------------------------------------------
# image ops (reference: src/operator/image/*.cc; HWC layout in, CHW out for
# to_tensor, matching mx.img semantics)
# ----------------------------------------------------------------------------

@register("_image_to_tensor")
def _image_to_tensor(data):
    if data.ndim == 3:
        return (data.astype(jnp.float32) / 255.0).transpose(2, 0, 1)
    return (data.astype(jnp.float32) / 255.0).transpose(0, 3, 1, 2)


@register("_image_normalize")
def _image_normalize(data, mean=(0.0,), std=(1.0,)):
    m = jnp.asarray(mean, jnp.float32)
    s = jnp.asarray(std, jnp.float32)
    shape = (-1, 1, 1) if data.ndim == 3 else (1, -1, 1, 1)
    return (data - m.reshape(shape)) / s.reshape(shape)


@register("_image_crop")
def _image_crop(data, x=0, y=0, width=1, height=1):
    if data.ndim == 3:
        return data[y:y + height, x:x + width, :]
    return data[:, y:y + height, x:x + width, :]


@register("_image_adjust_lighting")
def _image_adjust_lighting(data, alpha=(0.0, 0.0, 0.0)):
    """PCA-based AlexNet lighting jitter (parity: image_random-inl.h
    AdjustLightingImpl — same hard-coded eigval*eigvec table).  HWC (or
    NHWC) layout, channel-last like the reference's image namespace."""
    eig = jnp.asarray(
        [[55.46 * -0.5675, 4.794 * 0.7192, 1.148 * 0.4009],
         [55.46 * -0.5808, 4.794 * -0.0045, 1.148 * -0.8140],
         [55.46 * -0.5836, 4.794 * -0.6948, 1.148 * 0.4203]],
        jnp.float32)
    a = jnp.asarray(alpha, jnp.float32)
    if data.shape[-1] == 1:
        return data
    pca = eig @ a  # (3,) per-channel shift
    out = data.astype(jnp.float32) + pca
    if jnp.issubdtype(data.dtype, jnp.integer):
        # reference saturate_cast: clamp to the dtype's range, no wrap
        info = jnp.iinfo(data.dtype)
        out = jnp.clip(out, info.min, info.max)
    return out.astype(data.dtype)


@register("_image_random_lighting", needs_rng=True)
def _image_random_lighting(key, data, alpha_std=0.05):
    """Random lighting: alpha ~ N(0, alpha_std) per channel (parity:
    image_random.cc _image_random_lighting)."""
    a = jax.random.normal(key, (3,), jnp.float32) * alpha_std
    return _image_adjust_lighting(data, alpha=a)


@register("_image_resize")
def _image_resize(data, size=(), keep_ratio=False, interp=1):
    if isinstance(size, int):
        size = (size, size)
    w, h = int(size[0]), int(size[1]) if len(size) > 1 else int(size[0])
    method = "nearest" if interp == 0 else "linear"
    if data.ndim == 3:
        return jax.image.resize(data.astype(jnp.float32),
                                (h, w, data.shape[2]), method)
    return jax.image.resize(data.astype(jnp.float32),
                            (data.shape[0], h, w, data.shape[3]), method)


# ----------------------------------------------------------------------------
# spatial transformer family (reference: grid_generator.cc,
# spatial_transformer.cc, contrib/bilinear_resize.cc,
# contrib/adaptive_avg_pooling.cc)
# ----------------------------------------------------------------------------


def _affine_grid(theta, h, w):
    """theta (N, 6) -> normalized sampling grid (N, 2, H, W), xy order."""
    n = theta.shape[0]
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, H*W)
    t = theta.reshape(n, 2, 3)
    grid = jnp.einsum("nij,jk->nik", t, base)  # (N, 2, H*W)
    return grid.reshape(n, 2, h, w)


@register("GridGenerator")
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    h, w = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        return _affine_grid(data, h, w)
    # warp: data is a (N, 2, H, W) flow field in pixels; add to the base
    # grid and normalize to [-1, 1]
    n, _, fh, fw = data.shape
    gy, gx = jnp.meshgrid(jnp.arange(fh, dtype=data.dtype),
                          jnp.arange(fw, dtype=data.dtype), indexing="ij")
    x = (gx[None] + data[:, 0]) * 2.0 / max(fw - 1, 1) - 1.0
    y = (gy[None] + data[:, 1]) * 2.0 / max(fh - 1, 1) - 1.0
    return jnp.stack([x, y], axis=1)


def _bilinear_sample(data, grid):
    """Sample NCHW ``data`` at normalized xy ``grid`` (N,2,H',W')."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        got = jnp.take_along_axis(flat, jnp.broadcast_to(
            idx, (n, c, idx.shape[-1])), axis=2)
        return got.reshape(n, c, *gx.shape[1:])

    def inside(yi, xi):
        ok = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1))
        return ok.astype(data.dtype)[:, None]

    out = (gather(y0, x0) * inside(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * inside(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * inside(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * inside(y0 + 1, x0 + 1)
           * (wx * wy)[:, None])
    return out


@register("SpatialTransformer")
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine", sampler_type="bilinear",
                         cudnn_off=False):
    h, w = int(target_shape[0]), int(target_shape[1])
    grid = _affine_grid(loc, h, w)
    return _bilinear_sample(data, grid)


@register("_contrib_BilinearResize2D")
def _bilinear_resize2d(data, height=0, width=0, scale_height=None,
                       scale_width=None, mode="size"):
    n, c, h, w = data.shape
    oh = int(round(h * scale_height)) if scale_height else int(height)
    ow = int(round(w * scale_width)) if scale_width else int(width)
    # align-corners bilinear (matches the reference kernel)
    ys = jnp.linspace(0.0, h - 1, oh)
    xs = jnp.linspace(0.0, w - 1, ow)
    grid_x, grid_y = jnp.meshgrid(xs, ys)  # (oh, ow)
    gx = grid_x * 2.0 / max(w - 1, 1) - 1.0
    gy = grid_y * 2.0 / max(h - 1, 1) - 1.0
    grid = jnp.broadcast_to(jnp.stack([gx, gy])[None], (n, 2, oh, ow))
    return _bilinear_sample(data, grid)


@register("_contrib_AdaptiveAvgPooling2D")
def _adaptive_avg_pool2d(data, output_size=(1, 1)):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh = int(output_size[0])
    ow = int(output_size[1]) if len(output_size) > 1 else oh
    n, c, h, w = data.shape

    def axis_weights(in_len, out_len):
        # averaging matrix A (out_len, in_len): torch/mxnet adaptive
        # windows [floor(i*in/out), ceil((i+1)*in/out))
        import numpy as _np

        a = _np.zeros((out_len, in_len), _np.float32)
        for i in range(out_len):
            lo = (i * in_len) // out_len
            hi = -(-((i + 1) * in_len) // out_len)
            a[i, lo:hi] = 1.0 / (hi - lo)
        return jnp.asarray(a)

    ah = axis_weights(h, oh)
    aw = axis_weights(w, ow)
    return jnp.einsum("oh,nchw,pw->ncop", ah, data, aw,
                      precision=lax.Precision.HIGHEST)


# ----------------------------------------------------------------------------
# Correlation (reference: src/operator/correlation.cc — FlowNet cost volume)
# ----------------------------------------------------------------------------

@register("Correlation", num_outputs=2)
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    n, c, h, w = data1.shape
    pad = int(pad_size)
    k = int(kernel_size)
    bor = k // 2
    d = int(max_displacement) // int(stride2)
    s1, s2 = int(stride1), int(stride2)
    # extra bottom/right padding so the strided windows never overrun
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad + s1), (pad, pad + s1)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad + s1), (pad, pad + s1)))
    ph, pw = h + 2 * pad, w + 2 * pad
    oh = -(-(ph - 2 * bor - 2 * d * s2) // s1)
    ow = -(-(pw - 2 * bor - 2 * d * s2) // s1)
    base_y = d * s2 + bor
    sumelems = k * k * c
    outs = []
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            sy, sx = dy * s2, dx * s2
            a = lax.dynamic_slice(
                p1, (0, 0, base_y, base_y),
                (n, c, oh * s1, ow * s1))[:, :, ::s1, ::s1]
            b = lax.dynamic_slice(
                p2, (0, 0, base_y + sy, base_y + sx),
                (n, c, oh * s1, ow * s1))[:, :, ::s1, ::s1]
            prod = a * b if is_multiply else jnp.abs(a - b)
            outs.append(jnp.sum(prod, axis=1) / sumelems)
    out = jnp.stack(outs, axis=1)
    tmp = jnp.zeros_like(out)
    return out, tmp


# ----------------------------------------------------------------------------
# sharding constraint (GSPMD substrate, mxnet_tpu/sharding/): pins an
# intermediate's partitioning inside a trace.  ``sharding`` is a
# NamedSharding — hashable, so it rides the registry's static-attr cache
# keys; under jit the constraint is the GSPMD annotation, eagerly it is
# a device_put.  No reference counterpart (placement there is a device
# list, not a compiler annotation).
# ----------------------------------------------------------------------------


@register("_sharding_constraint")
def _sharding_constraint(data, sharding=None):
    if sharding is None:
        return data
    return lax.with_sharding_constraint(data, sharding)


# ----------------------------------------------------------------------------
# legacy/version aliases: the reference keeps *_v1 registrations of ops it
# later rewrote (batch_norm_v1.cc, convolution_v1.cc, pooling_v1.cc); here
# they are pure aliases of the modern kernels
# ----------------------------------------------------------------------------

alias("BatchNorm_v1", "BatchNorm")
alias("Convolution_v1", "Convolution")
alias("Pooling_v1", "Pooling")
alias("CuDNNBatchNorm", "BatchNorm")
alias("_CrossDeviceCopy", "identity")
alias("_contrib_backward_gradientmultiplier", "_contrib_gradientmultiplier")
