"""Contrib operators (reference: src/operator/contrib/).

The fused attention matmuls live in ops/nn.py; here: bounding-box / NMS-ish
utilities, FFT, index ops, and the boolean_mask family with static-shape
semantics (XLA needs static shapes; see each docstring for the deviation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("_contrib_fft")
def _fft(data, compute_size=128):
    out = jnp.fft.fft(data.astype(jnp.complex64))
    return jnp.stack([out.real, out.imag], axis=-1).reshape(
        data.shape[:-1] + (2 * data.shape[-1],)).astype(jnp.float32)


@register("_contrib_ifft")
def _ifft(data, compute_size=128):
    c = data.reshape(data.shape[:-1] + (data.shape[-1] // 2, 2))
    comp = c[..., 0] + 1j * c[..., 1]
    return jnp.fft.ifft(comp).real.astype(jnp.float32) * comp.shape[-1]


@register("_contrib_index_copy")
def _index_copy(old, idx, new):
    return old.at[idx.astype(jnp.int32)].set(new)


@register("_contrib_index_array")
def _index_array(data, axes=None):
    shape = data.shape
    if axes is None:
        axes = tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    return jnp.stack(grids, axis=-1).astype(jnp.int64)


@register("_contrib_getnnz")
def _getnnz(data, axis=None):
    return jnp.sum((data != 0).astype(jnp.int64), axis=axis)


@register("_contrib_gradientmultiplier")
def _gradientmultiplier(data, scalar=1.0):
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (g * scalar,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("_contrib_box_iou")
def _box_iou(lhs, rhs, format="corner"):
    """IoU matrix between two box sets (parity: bounding_box.cc box_iou)."""
    if format == "center":
        def to_corner(b):
            cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
            return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)

        lhs, rhs = to_corner(lhs), to_corner(rhs)
    l = lhs[..., :, None, :]
    r = rhs[..., None, :, :]
    tl = jnp.maximum(l[..., :2], r[..., :2])
    br = jnp.minimum(l[..., 2:], r[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_l = (l[..., 2] - l[..., 0]) * (l[..., 3] - l[..., 1])
    area_r = (r[..., 2] - r[..., 0]) * (r[..., 3] - r[..., 1])
    return inter / jnp.maximum(area_l + area_r - inter, 1e-12)


@register("_contrib_box_nms", num_outputs=2)
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
             score_index=1, id_index=-1, background_id=-1, force_suppress=False,
             in_format="corner", out_format="corner"):
    """Greedy NMS with static shapes via lax.fori_loop (suppressed → score -1)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])  # (B, N, E)
    B, N, E = flat.shape

    def nms_one(boxes):
        scores = boxes[:, score_index]
        order = jnp.argsort(-scores)
        sorted_boxes = boxes[order]
        coords = sorted_boxes[:, coord_start:coord_start + 4]
        ious = _box_iou(coords, coords, format=in_format)
        valid0 = sorted_boxes[:, score_index] > valid_thresh

        def body(i, keep):
            sup = jnp.logical_and(keep[i], ious[i] > overlap_thresh)
            sup = jnp.logical_and(sup, jnp.arange(N) > i)
            return jnp.logical_and(keep, ~sup)

        keep = lax.fori_loop(0, N, body, valid0)
        out = jnp.where(keep[:, None], sorted_boxes, -jnp.ones_like(sorted_boxes))
        return out, order.astype(jnp.float32)

    outs, idxs = jax.vmap(nms_one)(flat)
    return outs.reshape(shape), idxs.reshape(shape[:-1])


@register("_contrib_quantize", num_outputs=3)
def _quantize(data, min_range, max_range, out_type="uint8"):
    """Linear quantization (parity: src/operator/quantization/quantize.cc)."""
    if out_type == "uint8":
        qmin, qmax = 0.0, 255.0
        dt = jnp.uint8
    else:
        qmin, qmax = -127.0, 127.0
        dt = jnp.int8
    scale = (qmax - qmin) / jnp.maximum(max_range - min_range, 1e-12)
    q = jnp.clip(jnp.round((data - min_range) * scale + qmin), qmin, qmax)
    return q.astype(dt), min_range, max_range


@register("_contrib_dequantize")
def _dequantize(data, min_range, max_range, out_type="float32"):
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    else:
        qmin, qmax = -127.0, 127.0
    scale = (max_range - min_range) / (qmax - qmin)
    return (data.astype(jnp.float32) - qmin) * scale + min_range


@register("_contrib_count_sketch")
def _count_sketch(data, h, s, out_dim=16, processing_batch_size=32):
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., idx].add(data * sign)


# ----------------------------------------------------------------------------
# encoder-decoder interleaved attention matmuls (parity:
# src/operator/contrib/transformer.cc:650-780 — the encdec variants of the
# selfatt ops in ops/nn.py)
# ----------------------------------------------------------------------------

@register("_contrib_interleaved_matmul_encdec_qk")
def _interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """queries (Tq, B, H*D), keys_values (Tk, B, 2*H*D) → scaled QKᵀ
    (B*heads, Tq, Tk)."""
    tq, b, _ = queries.shape
    tk = keys_values.shape[0]
    q = queries.reshape(tq, b, heads, -1)
    d = q.shape[-1]
    kv = keys_values.reshape(tk, b, heads, 2, -1)
    k = kv[:, :, :, 0, :]
    q = jnp.transpose(q, (1, 2, 0, 3)).reshape(b * heads, tq, d)
    k = jnp.transpose(k, (1, 2, 0, 3)).reshape(b * heads, tk, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(q.dtype)
    return jnp.matmul(q * scale, jnp.swapaxes(k, -1, -2))


@register("_contrib_interleaved_matmul_encdec_valatt")
def _interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    """keys_values (Tk, B, 2*H*D), attention (B*heads, Tq, Tk) →
    (Tq, B, H*D)."""
    tk, b, _ = keys_values.shape
    kv = keys_values.reshape(tk, b, heads, 2, -1)
    v = kv[:, :, :, 1, :]
    d = v.shape[-1]
    v = jnp.transpose(v, (1, 2, 0, 3)).reshape(b * heads, tk, d)
    out = jnp.matmul(attention, v)  # (B*heads, Tq, D)
    tq = out.shape[1]
    out = out.reshape(b, heads, tq, d).transpose(2, 0, 1, 3)
    return out.reshape(tq, b, heads * d)


# ----------------------------------------------------------------------------
# Hawkes process log-likelihood (parity: src/operator/contrib/hawkes_ll.cc)
# ----------------------------------------------------------------------------

@register("_contrib_hawkesll", num_outputs=2,
          aliases=("_contrib_backward_hawkesll",))
def _hawkesll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Joint LL of K univariate Hawkes processes with exponential decay
    (hawkes_ll-inl.h hawkesll_forward + compensator), as one lax.scan."""
    n, t_len = lags.shape
    k = lda.shape[1]

    def one(mu_i, st0, lag_i, mark_i, vl_i, mt_i):
        def step(carry, inp):
            t, ll, st, last = carry
            lag_j, mark_j, j = inp
            valid = j < vl_i
            ci = mark_j.astype(jnp.int32)
            t_new = t + lag_j
            d = t_new - last[ci]
            ed = jnp.exp(-beta[ci] * d)
            intensity = mu_i[ci] + alpha[ci] * beta[ci] * st[ci] * ed
            comp = mu_i[ci] * d + alpha[ci] * st[ci] * (1 - ed)
            ll = ll + jnp.where(valid, jnp.log(intensity) - comp, 0.0)
            st = jnp.where(valid, st.at[ci].set(1 + st[ci] * ed), st)
            last = jnp.where(valid, last.at[ci].set(t_new), last)
            t = jnp.where(valid, t_new, t)
            return (t, ll, st, last), None

        init = (jnp.zeros(()), jnp.zeros(()), st0, jnp.zeros((k,)))
        (t, ll, st, last), _ = lax.scan(
            step, init,
            (lag_i, mark_i, jnp.arange(t_len, dtype=jnp.float32)))
        d = mt_i - last
        ed = jnp.exp(-beta * d)
        rem = mu_i * d + alpha * st * (1 - ed)
        return ll - jnp.sum(rem), ed * st

    return jax.vmap(one)(lda, state, lags,
                         marks.astype(jnp.int32), valid_length, max_time)


# ----------------------------------------------------------------------------
# boolean_mask: dynamic output shape → imperative host round-trip, the same
# forced sync the reference's dynamic-shape ops do
# (src/operator/contrib/boolean_mask.cc)
# ----------------------------------------------------------------------------


def _boolean_mask_override(inputs, attrs, out):
    import numpy as onp

    from .registry import invoke_fn

    # the mask sync is a host round-trip (dynamic output shape), but the
    # gather itself is traced via invoke_fn so autograd records a tape
    # node and gradients flow back to `data` (reference boolean_mask is
    # differentiable; its backward scatters into the kept rows)
    mask = inputs[1].asnumpy().astype(bool).reshape(-1)
    axis = int(attrs.get("axis", 0))
    idx = jnp.asarray(onp.nonzero(mask)[0], jnp.int32)
    (res,) = invoke_fn(
        lambda d: (jnp.take(d, idx, axis=axis),),
        [inputs[0]], op_name="_contrib_boolean_mask")
    return res


register("_contrib_boolean_mask")(lambda data, index, axis=0: data)
registry_mod = __import__("mxnet_tpu.ops.registry", fromlist=["x"])
registry_mod.register_invoke_override("_contrib_boolean_mask",
                                      _boolean_mask_override)


# ----------------------------------------------------------------------------
# DGL graph helpers on CSR structure (parity: src/operator/contrib/
# dgl_graph.cc edge_id / adjacency).  These operate on CSRNDArray via the
# imperative override hook (graph structure is host-resident, like the
# reference's CPU-only implementations).  The neighbor-sampling and
# graph-compaction ops (dgl_csr_neighbor_*_sample, dgl_subgraph,
# dgl_graph_compact) are DGL-integration glue below this framework's scope
# — DGL itself replaced them — and are intentionally not provided.
# ----------------------------------------------------------------------------


def _edge_id_override(inputs, attrs, out):
    import numpy as onp

    csr, u, v = inputs
    indptr = csr.indptr.asnumpy().astype(onp.int64)
    indices = csr.indices.asnumpy().astype(onp.int64)
    vals = csr.data_arr.asnumpy()
    uu = u.asnumpy().astype(onp.int64).reshape(-1)
    vv = v.asnumpy().astype(onp.int64).reshape(-1)
    res = onp.full(uu.shape, -1.0, onp.float32)
    for i, (r, c) in enumerate(zip(uu, vv)):
        lo, hi = indptr[r], indptr[r + 1]
        pos = onp.searchsorted(indices[lo:hi], c)
        if pos < hi - lo and indices[lo + pos] == c:
            res[i] = vals[lo + pos]
    from ..ndarray.ndarray import NDArray

    return NDArray(jnp.asarray(res))


def _dgl_adjacency_override(inputs, attrs, out):
    from ..ndarray import sparse as _sp

    csr = inputs[0]
    ones = type(csr.data_arr)(jnp.ones(csr.data_arr.shape, jnp.float32))
    return _sp.CSRNDArray(ones, csr.indptr, csr.indices, csr.shape)


register("_contrib_edge_id")(lambda data, u, v: data)
register("_contrib_dgl_adjacency")(lambda data: data)
registry_mod.register_invoke_override("_contrib_edge_id", _edge_id_override)
registry_mod.register_invoke_override("_contrib_dgl_adjacency",
                                      _dgl_adjacency_override)
