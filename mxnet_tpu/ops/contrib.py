"""Contrib operators (reference: src/operator/contrib/).

The fused attention matmuls live in ops/nn.py; here: bounding-box / NMS-ish
utilities, FFT, index ops, and the boolean_mask family with static-shape
semantics (XLA needs static shapes; see each docstring for the deviation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("_contrib_fft")
def _fft(data, compute_size=128):
    out = jnp.fft.fft(data.astype(jnp.complex64))
    return jnp.stack([out.real, out.imag], axis=-1).reshape(
        data.shape[:-1] + (2 * data.shape[-1],)).astype(jnp.float32)


@register("_contrib_ifft")
def _ifft(data, compute_size=128):
    c = data.reshape(data.shape[:-1] + (data.shape[-1] // 2, 2))
    comp = c[..., 0] + 1j * c[..., 1]
    return jnp.fft.ifft(comp).real.astype(jnp.float32) * comp.shape[-1]


@register("_contrib_index_copy")
def _index_copy(old, idx, new):
    return old.at[idx.astype(jnp.int32)].set(new)


@register("_contrib_index_array")
def _index_array(data, axes=None):
    shape = data.shape
    if axes is None:
        axes = tuple(range(len(shape)))
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    return jnp.stack(grids, axis=-1).astype(jnp.int64)


@register("_contrib_getnnz")
def _getnnz(data, axis=None):
    return jnp.sum((data != 0).astype(jnp.int64), axis=axis)


@register("_contrib_gradientmultiplier")
def _gradientmultiplier(data, scalar=1.0):
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (g * scalar,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("_contrib_box_iou")
def _box_iou(lhs, rhs, format="corner"):
    """IoU matrix between two box sets (parity: bounding_box.cc box_iou)."""
    if format == "center":
        def to_corner(b):
            cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
            return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)

        lhs, rhs = to_corner(lhs), to_corner(rhs)
    l = lhs[..., :, None, :]
    r = rhs[..., None, :, :]
    tl = jnp.maximum(l[..., :2], r[..., :2])
    br = jnp.minimum(l[..., 2:], r[..., 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_l = (l[..., 2] - l[..., 0]) * (l[..., 3] - l[..., 1])
    area_r = (r[..., 2] - r[..., 0]) * (r[..., 3] - r[..., 1])
    return inter / jnp.maximum(area_l + area_r - inter, 1e-12)


@register("_contrib_box_nms", num_outputs=2)
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1, coord_start=2,
             score_index=1, id_index=-1, background_id=-1, force_suppress=False,
             in_format="corner", out_format="corner"):
    """Greedy NMS with static shapes via lax.fori_loop (suppressed → score -1)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])  # (B, N, E)
    B, N, E = flat.shape

    def nms_one(boxes):
        scores = boxes[:, score_index]
        order = jnp.argsort(-scores)
        sorted_boxes = boxes[order]
        coords = sorted_boxes[:, coord_start:coord_start + 4]
        ious = _box_iou(coords, coords, format=in_format)
        valid0 = sorted_boxes[:, score_index] > valid_thresh

        def body(i, keep):
            sup = jnp.logical_and(keep[i], ious[i] > overlap_thresh)
            sup = jnp.logical_and(sup, jnp.arange(N) > i)
            return jnp.logical_and(keep, ~sup)

        keep = lax.fori_loop(0, N, body, valid0)
        out = jnp.where(keep[:, None], sorted_boxes, -jnp.ones_like(sorted_boxes))
        return out, order.astype(jnp.float32)

    outs, idxs = jax.vmap(nms_one)(flat)
    return outs.reshape(shape), idxs.reshape(shape[:-1])


@register("_contrib_quantize", num_outputs=3)
def _quantize(data, min_range, max_range, out_type="uint8"):
    """Linear quantization (parity: src/operator/quantization/quantize.cc)."""
    if out_type == "uint8":
        qmin, qmax = 0.0, 255.0
        dt = jnp.uint8
    else:
        qmin, qmax = -127.0, 127.0
        dt = jnp.int8
    scale = (qmax - qmin) / jnp.maximum(max_range - min_range, 1e-12)
    q = jnp.clip(jnp.round((data - min_range) * scale + qmin), qmin, qmax)
    return q.astype(dt), min_range, max_range


@register("_contrib_dequantize")
def _dequantize(data, min_range, max_range, out_type="float32"):
    if data.dtype == jnp.uint8:
        qmin, qmax = 0.0, 255.0
    else:
        qmin, qmax = -127.0, 127.0
    scale = (max_range - min_range) / (qmax - qmin)
    return (data.astype(jnp.float32) - qmin) * scale + min_range


@register("_contrib_count_sketch")
def _count_sketch(data, h, s, out_dim=16, processing_batch_size=32):
    idx = h.astype(jnp.int32).reshape(-1)
    sign = s.reshape(-1)
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., idx].add(data * sign)
