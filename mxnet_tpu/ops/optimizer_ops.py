"""Optimizer update operators.

Reference: ``src/operator/optimizer_op.cc`` — SGD/Adam/FTRL/... *as ops* so
updates run fused on-device, plus multi-tensor variants
(``multi_sgd_update`` etc., ``src/operator/contrib/multi_lamb.cc``).

TPU-native: each update is a small fused XLA computation.  The gluon Trainer
goes one step further and jits ONE update over the whole parameter pytree
(see optimizer/optimizer.py), which is the true multi-tensor path — these ops
exist for imperative/API parity and are used by the Updater.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("sgd_update")
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", num_outputs=2)
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("nag_mom_update", num_outputs=2)
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3)
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * m / (jnp.sqrt(v) + epsilon), m, v


@register("adamw_update", num_outputs=3)
def _adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight), m, v


@register("rmsprop_update", num_outputs=2)
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / (jnp.sqrt(new_n) + epsilon)
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", num_outputs=4)
def _rmspropalex_update(weight, grad, n, g_mean, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_gm = gamma1 * g_mean + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_gm) + epsilon)
    w = weight + new_delta
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_gm, new_delta


@register("ftrl_update", num_outputs=3)
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return w, new_z, new_n


@register("signsgd_update")
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2)
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return w, new_mom


@register("lamb_update_phase1")
def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                        epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight


@register("lamb_update_phase2")
def _lamb_update_phase2(weight, g, r1, r2, lr=0.01, lower_bound=-1.0,
                        upper_bound=-1.0):
    r1c = r1
    if lower_bound >= 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound >= 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2,
                      jnp.ones_like(r1c))
    return weight - lr * ratio * g


@register("multi_sum_sq", num_outputs=-1)
def _multi_sum_sq(*arrays, num_arrays=1):
    """Parity: src/operator/contrib/multi_sum_sq.cc (used by LARS/LAMB)."""
    return tuple(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays)
