"""Optimizer update operators.

Reference: ``src/operator/optimizer_op.cc`` — SGD/Adam/FTRL/... *as ops* so
updates run fused on-device, plus multi-tensor variants
(``multi_sgd_update`` etc., ``src/operator/contrib/multi_lamb.cc``).

TPU-native: each update is a small fused XLA computation.  The gluon Trainer
goes one step further and jits ONE update over the whole parameter pytree
(see optimizer/optimizer.py), which is the true multi-tensor path — these ops
exist for imperative/API parity and are used by the Updater.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("sgd_update")
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", num_outputs=2)
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("nag_mom_update", num_outputs=2)
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register("adam_update", num_outputs=3)
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - lr * m / (jnp.sqrt(v) + epsilon), m, v


@register("adamw_update", num_outputs=3)
def _adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    return weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight), m, v


@register("rmsprop_update", num_outputs=2)
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / (jnp.sqrt(new_n) + epsilon)
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register("rmspropalex_update", num_outputs=4)
def _rmspropalex_update(weight, grad, n, g_mean, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_gm = gamma1 * g_mean + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_gm) + epsilon)
    w = weight + new_delta
    if clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_gm, new_delta


@register("ftrl_update", num_outputs=3)
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return w, new_z, new_n


@register("signsgd_update")
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register("signum_update", num_outputs=2)
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * weight)
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(new_mom)
    return w, new_mom


@register("lamb_update_phase1")
def _lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                        epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh = m / (1 - beta1 ** t)
        vh = v / (1 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight


@register("lamb_update_phase2")
def _lamb_update_phase2(weight, g, r1, r2, lr=0.01, lower_bound=-1.0,
                        upper_bound=-1.0):
    r1c = r1
    if lower_bound >= 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound >= 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2,
                      jnp.ones_like(r1c))
    return weight - lr * ratio * g


@register("multi_sum_sq", num_outputs=-1)
def _multi_sum_sq(*arrays, num_arrays=1):
    """Parity: src/operator/contrib/multi_sum_sq.cc (used by LARS/LAMB)."""
    return tuple(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays)


# ---------------------------------------------------------------------------
# FTML (reference: FTMLKernel, src/operator/optimizer_op-inl.h:1205)
# ---------------------------------------------------------------------------

@register("ftml_update", num_outputs=4)
def _ftml_update(weight, grad, d, v, z, lr=0.1, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                 clip_grad=-1.0):
    g = rescale_grad * grad + wd * weight
    if clip_grad >= 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t))
                                   + epsilon)
    new_z = beta1 * z + (1 - beta1) * g - (d_t - beta1 * d) * weight
    return -new_z / d_t, d_t, new_v, new_z


# ---------------------------------------------------------------------------
# Mixed-precision single-tensor updates: bf16/fp16 weight + f32 master copy
# (reference: MP_SGD kernels, src/operator/optimizer_op-inl.h).  Functional
# deviation: the updated master weight is returned instead of written
# in place.
# ---------------------------------------------------------------------------

def _rescale_clip(grad, rescale_grad, clip_gradient):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register("mp_sgd_update", num_outputs=2)
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_outputs=3)
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       lazy_update=True):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + new_mom
    return w32.astype(weight.dtype), new_mom, w32


@register("mp_nag_mom_update", num_outputs=3)
def _mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    g = g + wd * weight32
    new_mom = momentum * mom + g
    w32 = weight32 - lr * (g + momentum * new_mom)
    return w32.astype(weight.dtype), new_mom, w32


@register("_adamw_update", num_outputs=3, inputs=("weight", "grad", "mean",
                                                  "var", "rescale_grad"))
def _adamw_update_op(weight, grad, mean, var, rescale_grad, lr=0.001,
                     beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0, eta=1.0,
                     clip_gradient=-1.0):
    """AdamW with the grad rescale as a device scalar (so a dynamic loss
    scale never forces a re-jit).  Parity: src/operator/contrib/adamw.cc."""
    g = grad * jnp.reshape(rescale_grad, ())
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight)
    return w, m, v


@register("_mp_adamw_update", num_outputs=4,
          inputs=("weight", "grad", "mean", "var", "weight32",
                  "rescale_grad"))
def _mp_adamw_update_op(weight, grad, mean, var, weight32, rescale_grad,
                        lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                        wd=0.0, eta=1.0, clip_gradient=-1.0):
    g = grad.astype(jnp.float32) * jnp.reshape(rescale_grad, ())
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    w32 = weight32 - eta * (lr * m / (jnp.sqrt(v) + epsilon) + wd * weight32)
    return w32.astype(weight.dtype), m, v, w32


# ---------------------------------------------------------------------------
# Multi-tensor updates (reference: MultiSGD*, src/operator/optimizer_op.cc;
# preloaded_* take lrs/wds as device tensors).  One jitted XLA computation
# updates every tensor — the fusion the reference needed hand-written CUDA
# kernels for.  Outputs: updated weights for each tensor, then updated
# state tensors (reference updates states in place).
# ---------------------------------------------------------------------------

def _multi_sgd_core(arrays, stride, lrs, wds, momentum, rescale_grad,
                    clip_gradient, has_mom, has_mp):
    n = len(arrays) // stride
    ws, moms, w32s = [], [], []
    for i in range(n):
        grp = arrays[i * stride:(i + 1) * stride]
        w, g = grp[0], grp[1]
        mom = grp[2] if has_mom else None
        w32 = grp[-1] if has_mp else w
        lr, wd = lrs[i], wds[i]  # floats (attrs) or device scalars (preloaded)
        gg = _rescale_clip(g, rescale_grad, clip_gradient) \
            if has_mp else g * rescale_grad
        if not has_mp and clip_gradient >= 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        if has_mom:
            new_mom = momentum * mom - lr * (gg + wd * w32)
            new_w32 = w32 + new_mom
            moms.append(new_mom)
        else:
            new_w32 = w32 - lr * (gg + wd * w32)
        if has_mp:
            ws.append(new_w32.astype(w.dtype))
            w32s.append(new_w32)
        else:
            ws.append(new_w32)
    return tuple(ws) + tuple(moms) + tuple(w32s)


@register("multi_sgd_update", num_outputs=-1)
def _multi_sgd_update(*arrays, lrs=(), wds=(), rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=1):
    return _multi_sgd_core(arrays, 2, lrs, wds, 0.0, rescale_grad,
                           clip_gradient, False, False)


@register("multi_sgd_mom_update", num_outputs=-1)
def _multi_sgd_mom_update(*arrays, lrs=(), wds=(), momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=1):
    return _multi_sgd_core(arrays, 3, lrs, wds, momentum, rescale_grad,
                           clip_gradient, True, False)


@register("multi_mp_sgd_update", num_outputs=-1)
def _multi_mp_sgd_update(*arrays, lrs=(), wds=(), rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=1):
    return _multi_sgd_core(arrays, 3, lrs, wds, 0.0, rescale_grad,
                           clip_gradient, False, True)


@register("multi_mp_sgd_mom_update", num_outputs=-1)
def _multi_mp_sgd_mom_update(*arrays, lrs=(), wds=(), momentum=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             num_weights=1):
    return _multi_sgd_core(arrays, 4, lrs, wds, momentum, rescale_grad,
                           clip_gradient, True, True)


def _preloaded_core(arrays, stride, momentum, rescale_grad, clip_gradient,
                    has_mom, has_mp):
    lrs_t, wds_t = arrays[-2], arrays[-1]
    body = arrays[:-2]
    n = len(body) // stride
    lrs = [lrs_t[i] for i in range(n)]
    wds = [wds_t[i] for i in range(n)]
    return _multi_sgd_core(body, stride, lrs, wds, momentum, rescale_grad,
                           clip_gradient, has_mom, has_mp)


@register("preloaded_multi_sgd_update", num_outputs=-1)
def _preloaded_multi_sgd_update(*arrays, rescale_grad=1.0,
                                clip_gradient=-1.0, num_weights=1):
    return _preloaded_core(arrays, 2, 0.0, rescale_grad, clip_gradient,
                           False, False)


@register("preloaded_multi_sgd_mom_update", num_outputs=-1)
def _preloaded_multi_sgd_mom_update(*arrays, momentum=0.0, rescale_grad=1.0,
                                    clip_gradient=-1.0, num_weights=1):
    return _preloaded_core(arrays, 3, momentum, rescale_grad, clip_gradient,
                           True, False)


@register("preloaded_multi_mp_sgd_update", num_outputs=-1)
def _preloaded_multi_mp_sgd_update(*arrays, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=1):
    return _preloaded_core(arrays, 3, 0.0, rescale_grad, clip_gradient,
                           False, True)


@register("preloaded_multi_mp_sgd_mom_update", num_outputs=-1)
def _preloaded_multi_mp_sgd_mom_update(*arrays, momentum=0.0,
                                       rescale_grad=1.0, clip_gradient=-1.0,
                                       num_weights=1):
    return _preloaded_core(arrays, 4, momentum, rescale_grad, clip_gradient,
                           True, True)


@register("multi_lars")
def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
                eps=1e-8, rescale_grad=1.0):
    """LARS coefficients from per-tensor norms (parity:
    src/operator/contrib/multi_lars-inl.h MultiLARSKernel)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    valid = jnp.logical_and(w_norm > 0, grads_sum_sq > 0)
    lars = lrs * eta * w_norm / (jnp.sqrt(grads_sum_sq) * rescale_grad
                                 + wds * w_norm + eps)
    return jnp.where(valid, lars, lrs)


@register("mp_lamb_update_phase1")
def _mp_lamb_update_phase1(weight, grad, mean, var, weight32, beta1=0.9,
                           beta2=0.999, epsilon=1e-6, t=1,
                           bias_correction=True, wd=0.0, rescale_grad=1.0,
                           clip_gradient=-1.0):
    g = _rescale_clip(grad, rescale_grad, clip_gradient)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh, vh = m / (1 - beta1 ** t), v / (1 - beta2 ** t)
    else:
        mh, vh = m, v
    return mh / (jnp.sqrt(vh) + epsilon) + wd * weight32


@register("mp_lamb_update_phase2", num_outputs=2)
def _mp_lamb_update_phase2(weight, g, r1, r2, weight32, lr=0.01,
                           lower_bound=-1.0, upper_bound=-1.0):
    r1c = r1
    if lower_bound >= 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound >= 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2,
                      jnp.ones_like(r1c))
    w32 = weight32 - lr * ratio * g
    return w32.astype(weight.dtype), w32


def _lamb_step(w32, g, m, v, lr, wd, beta1, beta2, epsilon, step,
               bias_correction, lower_bound, upper_bound):
    new_m = beta1 * m + (1 - beta1) * g
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mh, vh = new_m / (1 - beta1 ** step), new_v / (1 - beta2 ** step)
    else:
        mh, vh = new_m, new_v
    upd = mh / (jnp.sqrt(vh) + epsilon) + wd * w32
    r1 = jnp.sqrt(jnp.sum(jnp.square(w32)))
    if lower_bound >= 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound >= 0:
        r1 = jnp.minimum(r1, upper_bound)
    r2 = jnp.sqrt(jnp.sum(jnp.square(upd)))
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    return w32 - lr * ratio * upd, new_m, new_v


@register("_multi_lamb_update", num_outputs=-1)
def _multi_lamb_update(*arrays, learning_rates=(), wds=(), step_count=(),
                       beta1=0.9, beta2=0.999, epsilon=1e-6,
                       rescale_grad=1.0, lower_bound=-1.0, upper_bound=-1.0,
                       clip_gradient=-1.0, bias_correction=True,
                       num_tensors=1):
    """Fused LAMB over a tensor list (parity:
    src/operator/contrib/multi_lamb.cc) — one XLA computation, no
    hand-written multi-tensor CUDA kernel needed."""
    n = len(arrays) // 4
    outs, ms, vs = [], [], []
    for i in range(n):
        w, g, m, v = arrays[i * 4:(i + 1) * 4]
        gg = _rescale_clip(g, rescale_grad, clip_gradient)
        w2, m2, v2 = _lamb_step(w, gg, m, v, float(learning_rates[i]),
                                float(wds[i]), beta1, beta2, epsilon,
                                int(step_count[i]), bias_correction,
                                lower_bound, upper_bound)
        outs.append(w2), ms.append(m2), vs.append(v2)
    return tuple(outs) + tuple(ms) + tuple(vs)


@register("_multi_mp_lamb_update", num_outputs=-1)
def _multi_mp_lamb_update(*arrays, learning_rates=(), wds=(), step_count=(),
                          beta1=0.9, beta2=0.999, epsilon=1e-6,
                          rescale_grad=1.0, lower_bound=-1.0,
                          upper_bound=-1.0, clip_gradient=-1.0,
                          bias_correction=True, num_tensors=1):
    n = len(arrays) // 5
    outs, ms, vs, w32s = [], [], [], []
    for i in range(n):
        w, g, m, v, w32 = arrays[i * 5:(i + 1) * 5]
        gg = _rescale_clip(g, rescale_grad, clip_gradient)
        w2, m2, v2 = _lamb_step(w32, gg, m, v, float(learning_rates[i]),
                                float(wds[i]), beta1, beta2, epsilon,
                                int(step_count[i]), bias_correction,
                                lower_bound, upper_bound)
        outs.append(w2.astype(w.dtype))
        ms.append(m2), vs.append(v2), w32s.append(w2)
    return tuple(outs) + tuple(ms) + tuple(vs) + tuple(w32s)


@register("_multi_adamw_update", num_outputs=-1)
def _multi_adamw_update(*arrays, lrs=(), wds=(), etas=(), beta1=0.9,
                        beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                        num_weights=1):
    """Fused AdamW over a tensor list; last input is the device-scalar grad
    rescale (parity: src/operator/contrib/adamw.cc)."""
    scale = jnp.reshape(arrays[-1], ())
    body = arrays[:-1]
    n = len(body) // 4
    outs, ms, vs = [], [], []
    for i in range(n):
        w, g, m, v = body[i * 4:(i + 1) * 4]
        gg = g * scale
        if clip_gradient >= 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        m2 = beta1 * m + (1 - beta1) * gg
        v2 = beta2 * v + (1 - beta2) * jnp.square(gg)
        w2 = w - float(etas[i]) * (float(lrs[i]) * m2
                                   / (jnp.sqrt(v2) + epsilon)
                                   + float(wds[i]) * w)
        outs.append(w2), ms.append(m2), vs.append(v2)
    return tuple(outs) + tuple(ms) + tuple(vs)


@register("_multi_mp_adamw_update", num_outputs=-1)
def _multi_mp_adamw_update(*arrays, lrs=(), wds=(), etas=(), beta1=0.9,
                           beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                           num_weights=1):
    scale = jnp.reshape(arrays[-1], ())
    body = arrays[:-1]
    n = len(body) // 5
    outs, ms, vs, w32s = [], [], [], []
    for i in range(n):
        w, g, m, v, w32 = body[i * 5:(i + 1) * 5]
        gg = g.astype(jnp.float32) * scale
        if clip_gradient >= 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        m2 = beta1 * m + (1 - beta1) * gg
        v2 = beta2 * v + (1 - beta2) * jnp.square(gg)
        w2 = w32 - float(etas[i]) * (float(lrs[i]) * m2
                                     / (jnp.sqrt(v2) + epsilon)
                                     + float(wds[i]) * w32)
        outs.append(w2.astype(w.dtype))
        ms.append(m2), vs.append(v2), w32s.append(w2)
    return tuple(outs) + tuple(ms) + tuple(vs) + tuple(w32s)


# ---------------------------------------------------------------------------
# AdaGrad (dense kernels usable with row-sparse grads through the sparse
# dispatch layer; reference: _sparse_adagrad_update in optimizer_op.cc and
# group_adagrad in contrib/optimizer_op-inl.h)
# ---------------------------------------------------------------------------

@register("_sparse_adagrad_update", num_outputs=2)
def _sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                           wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Reference parity: AdagradDnsRspDnsKernel (optimizer_op.cc) divides by
    sqrt(hist + eps), and the sparse path rejects weight decay
    (CheckAdagradParam requires wd == 0)."""
    if float(wd) != 0.0:
        raise ValueError("_sparse_adagrad_update: wd must be 0 "
                         "(reference sparse AdaGrad rejects weight decay)")
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_hist = history + jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_hist + epsilon)
    return w, new_hist


@register("_contrib_group_adagrad_update", num_outputs=2)
def _group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                          clip_gradient=-1.0, epsilon=1e-5):
    """Per-row (group) AdaGrad: one shared accumulator per embedding row
    (parity: GroupAdagradDnsRspKernel)."""
    g = grad * rescale_grad
    if clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    row_axes = tuple(range(1, g.ndim))
    ssq = jnp.mean(jnp.square(g), axis=row_axes) if g.ndim > 1 \
        else jnp.square(g)
    new_hist = history + jnp.reshape(ssq, history.shape)
    denom = jnp.sqrt(new_hist + epsilon)
    denom = jnp.reshape(denom, (-1,) + (1,) * (g.ndim - 1))
    return weight - lr * g / denom, new_hist


# ---------------------------------------------------------------------------
# Gradient hygiene helpers used by AMP/LARS drivers (reference:
# all_finite.cc, reset_arrays.cc)
# ---------------------------------------------------------------------------

@register("all_finite")
def _all_finite(data, init_output=True):
    """1.0 iff every element is finite.  Functional deviation: with
    ``init_output=False`` the reference ANDs into the existing output
    buffer; here the caller ANDs results instead."""
    return jnp.isfinite(data).all().astype(jnp.float32).reshape((1,))


@register("multi_all_finite")
def _multi_all_finite(*arrays, num_arrays=1, init_output=True):
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.astype(jnp.float32).reshape((1,))


@register("reset_arrays", num_outputs=-1)
def _reset_arrays(*arrays, num_arrays=1):
    return tuple(jnp.zeros_like(a) for a in arrays)
