"""Pallas TPU kernel: paged attention over the serve arena's block tables.

The serving decode/verify hot loop (``serve/model.py build_step_fn``)
historically paid stock XLA paging: ``kv[li, block_table]`` materializes
every lane's full ``(B, max_pages·page_size, KV, D)`` context in HBM each
step, and an int8 arena additionally materializes a full fp32 dequantized
copy before attention starts.  This module is the vLLM/PagedAttention
pattern instead: a Pallas kernel whose grid walks ``(batch-lane, kv-head,
page)``, prefetches the block table as scalars so each step DMAs exactly
one ``(page_size, D)`` page tile into VMEM, dequantizes in-register off
the per-(layer, page) scale, and accumulates flash-style online softmax.
HBM traffic drops from O(ctx·KV·D) gathered+dequantized per step to the
pages actually stored, and GQA never replicates K/V ``H/KV``-fold — the
query is folded to ``(B, KV, k1·H/KV, D)`` so grouped heads share one
page load.

Semantics (shared by kernel and reference): query ``j`` of lane ``b``
sits at position ``positions[b] + j`` and attends context positions
``<= positions[b] + j`` on that lane's pages only; page 0 is the arena's
reserved null page and is always masked (an active lane's live context
never maps to page 0, so this only zeroes inactive-lane garbage the
scheduler discards anyway).  Fully-masked query rows return 0.

Registered as ``_contrib_paged_attention`` so the op-consistency harness
and mxlint cover it like any other op; ``use_kernel`` picks the path:
``0`` = pure-jnp reference, ``1`` = force the Pallas kernel (compiled on
TPU, interpreter elsewhere — CI parity runs), unset/``auto`` = kernel on
TPU, reference elsewhere (the interpreter is correct but slow; off-TPU
production decode should take the XLA reference, not emulation).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .pallas_kernels import _EAGER_JIT_CACHE, _LANES, _platform_pick
from .registry import register


def _paged_ref(q, k_pages, v_pages, block_table, positions, *scales,
               scale):
    """Pure-jnp reference: gather + (dequant) + grouped-GQA attention.

    Matches the kernel's masking exactly (position AND null-page); the
    softmax is the plain two-pass form with fully-masked rows guarded
    to zero output.
    """
    b, k1, h, d = q.shape
    s_page, kv = k_pages.shape[1], k_pages.shape[2]
    maxp = block_table.shape[1]
    grp = h // kv
    ctx = maxp * s_page
    keys = k_pages[block_table].astype(jnp.float32)  # (B, maxp, S, KV, D)
    vals = v_pages[block_table].astype(jnp.float32)
    if scales:
        ks, vs = scales
        keys = keys * ks[block_table][..., None, None, None]
        vals = vals * vs[block_table][..., None, None, None]
    keys = keys.reshape(b, ctx, kv, d)
    vals = vals.reshape(b, ctx, kv, d)
    qg = q.astype(jnp.float32).reshape(b, k1, kv, grp, d)
    s = jnp.einsum("bkvgd,bcvd->bkvgc", qg, keys) * scale
    posk = positions[:, None] + jnp.arange(k1)[None, :]      # (B, k1)
    ok = (jnp.arange(ctx)[None, None, :] <= posk[..., None]) \
        & jnp.repeat(block_table != 0, s_page, axis=1)[:, None, :]
    s = jnp.where(ok[:, :, None, None, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isfinite(m), m, 0.0)   # all-masked row -> exp(-inf)
    p = jnp.exp(s - m)
    l = p.sum(axis=-1, keepdims=True)
    att = jnp.einsum("bkvgc,bcvd->bkvgd",
                     p / jnp.where(l == 0, 1.0, l), vals)
    return att.reshape(b, k1, h, d).astype(q.dtype)


def _paged_kernel(tbl_ref, pos_ref, *refs, grp, page, scale, quantized):
    """One (lane, kv-head, page) grid step of online-softmax attention.

    The page axis is innermost — Pallas TPU runs the grid sequentially,
    so the VMEM scratch ``(m, l, acc)`` carries across a lane's pages
    and is (re)initialized whenever the page index wraps to 0.  The
    block table itself is a scalar-prefetch operand: the k/v BlockSpec
    index maps read ``tbl[b, p]`` so the pipeline DMAs exactly the page
    the table names (the null page 0 is still fetched but fully masked).
    """
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, \
            acc_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, -jnp.inf, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    pid = tbl_ref[b, p]
    q = q_ref[0, 0].astype(jnp.float32) * scale              # (QG, D)
    k = k_ref[0, :, 0, :].astype(jnp.float32)                # (S, D)
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    if quantized:
        k = k * ks_ref[pid]
        v = v * vs_ref[pid]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)  # (QG, S)
    qg = s.shape[0]
    # query row r is head-group r % grp of query token r // grp; its
    # absolute position is positions[b] + r // grp
    row = lax.broadcasted_iota(jnp.int32, (qg, page), 0) // grp
    col = p * page + lax.broadcasted_iota(jnp.int32, (qg, page), 1)
    ok = (col <= pos_ref[b] + row) & (pid != 0)
    s = jnp.where(ok, s, -jnp.inf)

    m = m_ref[...][:, :1]                                    # (QG, 1)
    l = l_ref[...][:, :1]
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    # a fully-null prefix keeps m_new = -inf; exp against 0 instead so
    # masked rows contribute exact zeros rather than nans
    safe_m = jnp.where(m_new == -jnp.inf, 0.0, m_new)
    pmat = jnp.exp(s - safe_m)
    alpha = jnp.exp(m - safe_m)
    l_new = l * alpha + pmat.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + lax.dot_general(
        pmat, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = lax.broadcast_in_dim(m_new[:, 0], m_ref.shape, (0,))
    l_ref[...] = lax.broadcast_in_dim(l_new[:, 0], l_ref.shape, (0,))

    @pl.when(p == n_p - 1)
    def _done():
        lf = l_ref[...][:, :1]
        o_ref[0, 0] = (acc_ref[...]
                       / jnp.where(lf == 0, 1.0, lf)).astype(o_ref.dtype)


def _paged_pallas(q, k_pages, v_pages, block_table, positions, *scales,
                  scale, grp, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, k1, h, d = q.shape
    s_page, kv = k_pages.shape[1], k_pages.shape[2]
    maxp = block_table.shape[1]
    qg = k1 * grp
    # fold GQA into the query: (B, k1, H, D) -> (B, KV, k1*G, D) with
    # row r = j*G + g <-> head h = kv*G + g (the jnp.repeat ordering),
    # so grouped heads ride one page load instead of replicating K/V
    q4 = q.reshape(b, k1, kv, grp, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b, kv, qg, d)
    quant = bool(scales)
    kernel = functools.partial(_paged_kernel, grp=grp, page=s_page,
                               scale=scale, quantized=quant)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2 + len(scales),
        grid=(b, kv, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, qg, d), lambda b, kv, p, *s: (b, kv, 0, 0)),
            pl.BlockSpec((1, s_page, 1, d),
                         lambda b, kv, p, *s: (s[0][b, p], 0, kv, 0)),
            pl.BlockSpec((1, s_page, 1, d),
                         lambda b, kv, p, *s: (s[0][b, p], 0, kv, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, qg, d),
                               lambda b, kv, p, *s: (b, kv, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((qg, _LANES), jnp.float32),
            pltpu.VMEM((qg, _LANES), jnp.float32),
            pltpu.VMEM((qg, d), jnp.float32),
        ],
    )
    out4 = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv, qg, d), q.dtype),
        interpret=interpret,
    )(block_table, positions, *scales, q4, k_pages, v_pages)
    return out4.reshape(b, kv, k1, grp, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b, k1, h, d)


@register("_contrib_paged_attention",
          inputs=("query", "k_pages", "v_pages", "block_table",
                  "positions", "k_scale", "v_scale"))
def paged_attention(query, k_pages, v_pages, block_table, positions,
                    k_scale=None, v_scale=None, scale=None,
                    use_kernel=None):
    """Paged attention over block tables: ``(B, k1, H, D)`` queries
    against ``(P, S, KV, D)`` K/V pages addressed by a ``(B, maxp)``
    int32 block table, one scalar position per lane.

    ``k1`` is the query width — 1 for decode, ``spec_k + 1`` for
    speculative verify; query ``j`` attends positions
    ``<= positions[b] + j``.  Page 0 is the reserved null page and is
    always masked.  ``k_scale``/``v_scale`` ``(P,)`` f32, when given,
    dequantize int8 pages in-register.  ``scale`` defaults to
    ``1/sqrt(D)``.  ``use_kernel``: ``0`` reference, ``1`` force the
    Pallas kernel (interpreter off-TPU), unset = kernel on TPU only.

    TPU note: the kernel's page tile is ``(page_size, D)`` per kv-head —
    compiled Mosaic wants ``page_size`` a multiple of 8 and ``D`` of
    128; smaller geometries (tests) run the interpreter or reference.
    """
    if (k_scale is None) != (v_scale is None):
        raise MXNetError("_contrib_paged_attention needs both k_scale "
                         "and v_scale or neither")
    if query.ndim != 4 or k_pages.ndim != 4:
        raise MXNetError(
            "_contrib_paged_attention wants query (B, k1, H, D) and "
            "pages (P, S, KV, D); got %s / %s"
            % (query.shape, k_pages.shape))
    h, d = query.shape[2], query.shape[3]
    kv = k_pages.shape[2]
    if h % kv or k_pages.shape[3] != d:
        raise MXNetError(
            "_contrib_paged_attention: %d query heads do not group over "
            "%d kv heads (head_dim %d vs %d)"
            % (h, kv, d, k_pages.shape[3]))
    if scale is None or scale == 0:
        scale = 1.0 / (d ** 0.5)
    scale = float(scale)
    block_table = block_table.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    scales = () if k_scale is None else (k_scale.astype(jnp.float32),
                                         v_scale.astype(jnp.float32))
    args = (query, k_pages, v_pages, block_table, positions) + scales
    mode = "auto" if use_kernel is None or str(use_kernel) == "auto" \
        else str(int(use_kernel))
    krun = functools.partial(_paged_pallas, scale=scale, grp=h // kv)
    rrun = functools.partial(_paged_ref, scale=scale)
    if mode == "0":
        return rrun(*args)
    # Platform is resolved from the backend, NOT via
    # jax.lax.platform_dependent: on this jax version the cond over the
    # platform index still LOWERS every branch, and the compiled-pallas
    # branch refuses to lower for cpu — so a traced platform_dependent
    # poisons every CPU jit that touches the op (the serving graphs).
    # default_backend() is a host-side query, safe under trace; serving
    # executables are always compiled for the default backend anyway.
    from jax import core as _core

    traced = any(isinstance(a, _core.Tracer) for a in args)
    on_tpu = jax.default_backend() == "tpu"
    if mode == "1":
        # forced kernel: compiled on TPU, interpreter elsewhere (the
        # interpreter traces to plain jax ops, so it serializes into
        # AOT bundles — the CI parity path)
        if traced:
            return krun(*args, interpret=not on_tpu)
        return _platform_pick(krun, *args)
    # auto: compiled kernel on TPU, XLA reference elsewhere (the
    # interpreter is for parity tests, not production CPU decode)
    if on_tpu:
        return krun(*args, interpret=False) if traced \
            else _platform_pick(krun, *args)
    if traced:
        return rrun(*args)
    key = (_paged_ref, ("scale", scale), "ref")
    fn = _EAGER_JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(rrun)
        _EAGER_JIT_CACHE[key] = fn
    return fn(*args)
