"""Control-flow operators: ``foreach`` / ``while_loop`` / ``cond``.

Reference: ``src/operator/control_flow.cc:475-531`` (``_foreach``,
``_while_loop``, ``_cond`` — stateful ops executing sub-CachedOps) and the
Python frontend ``python/mxnet/ndarray/contrib.py`` (foreach:216,
while_loop:360, cond:537).

TPU-native design: the Python body is traced ONCE over NDArray-wrapped
tracers (the same trick ``hybridize()`` uses) and lowered to a single
``lax.scan`` / masked-scan / ``lax.cond`` — XLA-compilable, so a foreach
inside a jitted train step costs one fused loop instead of per-iteration
dispatch.  Gradients flow through ``registry.invoke_fn`` (tape node with a
re-linearizable prim), so first- and higher-order autograd work.

Deviations (all from XLA's static-shape rule):
- ``while_loop`` always runs ``max_iterations`` scan steps with a liveness
  mask; outputs are padded to ``max_iterations`` rows (the reference's
  *symbolic* while_loop does the same; its imperative one trims).
- ``cond`` evaluates the predicate eagerly when it is concrete (imperative
  mode — only the taken branch runs, like the reference); under a trace it
  lowers to ``lax.cond`` with both branches traced.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .. import autograd
from . import registry as _reg


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def foreach(body, data, init_states):
    """Iterate ``body(data_t, states) -> (outputs, new_states)`` over axis 0.

    Parity: ``mx.nd.contrib.foreach`` (ndarray/contrib.py:216).  Returns
    (outputs stacked on axis 0, final states), mirroring the input nesting
    (single NDArray in → single NDArray out).
    """
    from ..ndarray.ndarray import NDArray

    data_list = _as_list(data)
    states_list = _as_list(init_states)
    data_single = not isinstance(data, (list, tuple))
    states_single = not isinstance(init_states, (list, tuple))
    n_data, n_states = len(data_list), len(states_list)

    if autograd.is_recording():
        # imperative reference semantics (ndarray/contrib.py foreach is a
        # Python loop): every step tapes normally, so gradients also flow
        # to arrays the body merely closes over — which the one-op scan
        # lowering below cannot see.
        from .. import ndarray as nd

        T = data_list[0].shape[0]
        states = init_states
        outs_acc = None
        out_single = True
        for t in range(T):
            xs = [d[t] for d in data_list]
            outs, states = body(xs[0] if data_single else xs, states)
            outs_l = _as_list(outs)
            if outs_acc is None:
                outs_acc = [[] for _ in outs_l]
                out_single = not isinstance(outs, (list, tuple))
            for acc, o in zip(outs_acc, outs_l):
                acc.append(o)
        stacked = [nd.stack(*acc, axis=0) for acc in (outs_acc or [])]
        if outs_acc is None:
            return [], states
        return (stacked[0] if out_single else stacked), states

    meta = {}

    def fn(*arrays):
        xs = list(arrays[:n_data])
        carry0 = list(arrays[n_data:])

        def step(carry, x):
            with autograd.pause():
                xs_nd = [NDArray(a) for a in x]
                st_nd = [NDArray(a) for a in carry]
                outs, new_states = body(
                    xs_nd[0] if data_single else xs_nd,
                    st_nd[0] if states_single else st_nd)
            outs_l = _as_list(outs)
            ns_l = _as_list(new_states)
            meta["n_out"] = len(outs_l)
            meta["out_single"] = not isinstance(outs, (list, tuple))
            return ([s.data() for s in ns_l],
                    [o.data() for o in outs_l])

        final, ys = lax.scan(step, carry0, xs)
        return tuple(ys) + tuple(final)

    results = _reg.invoke_fn(fn, data_list + states_list, op_name="_foreach")
    n_out = meta["n_out"]
    outs, states = results[:n_out], results[n_out:]
    if meta["out_single"]:
        outs = outs[0]
    if states_single:
        states = states[0]
    return outs, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Loop ``func(*loop_vars) -> (outputs, new_loop_vars)`` while
    ``cond(*loop_vars)`` holds, at most ``max_iterations`` times.

    Parity: ``mx.nd.contrib.while_loop`` (ndarray/contrib.py:360).  Lowered
    to a masked ``lax.scan`` of length ``max_iterations`` so the loop is
    reverse-differentiable and static-shaped; rows of ``outputs`` beyond
    the actual step count are zero (symbolic-mode padding semantics).
    """
    from ..ndarray.ndarray import NDArray

    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations on TPU "
                         "(static shapes)")
    lv_list = _as_list(loop_vars)
    n_lv = len(lv_list)

    if autograd.is_recording():
        # imperative reference semantics: eager Python loop, outputs
        # trimmed to actual steps (ndarray-mode while_loop), grads taped
        # per step (incl. closure-captured arrays)
        from .. import ndarray as nd

        lv = list(lv_list)
        outs_acc = None
        steps = 0
        # the ndarray-mode while_loop's host cond is its documented
        # semantics (one pull per step)  # mxlint: allow-host-sync
        while steps < max_iterations and bool(cond(*lv).asnumpy().item()):
            outs, new_lv = func(*lv)
            lv = _as_list(new_lv)
            outs_l = _as_list(outs)
            if outs_acc is None:
                outs_acc = [[] for _ in outs_l]
                out_single = not isinstance(outs, (list, tuple))
            for acc, o in zip(outs_acc, outs_l):
                acc.append(o)
            steps += 1
        stacked = [nd.stack(*acc, axis=0) for acc in (outs_acc or [])]
        if outs_acc is None:
            stacked, out_single = [], True
        outs_ret = (stacked[0] if out_single and stacked else stacked)
        lv_ret = lv if isinstance(loop_vars, (list, tuple)) else lv[0]
        return outs_ret, lv_ret

    meta = {}

    def fn(*arrays):
        lv0 = list(arrays)

        def trace_cond(lv):
            with autograd.pause():
                p = cond(*[NDArray(a) for a in lv])
            return p.data().astype(jnp.bool_).reshape(())

        def trace_step(lv):
            with autograd.pause():
                outs, new_lv = func(*[NDArray(a) for a in lv])
            outs_l = _as_list(outs)
            new_l = _as_list(new_lv)
            meta["n_out"] = len(outs_l)
            meta["out_single"] = not isinstance(outs, (list, tuple))
            if len(new_l) != n_lv:
                raise MXNetError("func must return as many loop_vars as it "
                                 "received")
            return ([o.data() for o in outs_l],
                    [s.data() for s in new_l])

        def step(carry, _):
            alive, lv = carry
            outs, new_lv = trace_step(lv)
            lv_next = [jnp.where(alive, n, o) for n, o in zip(new_lv, lv)]
            ys = [jnp.where(alive, o, jnp.zeros_like(o)) for o in outs]
            alive_next = jnp.logical_and(alive, trace_cond(lv_next))
            return (alive_next, lv_next), (ys, alive)

        alive0 = trace_cond(lv0)
        (_, lv_fin), (ys, alive_hist) = lax.scan(
            step, (alive0, lv0), None, length=int(max_iterations))
        n_steps = jnp.sum(alive_hist.astype(jnp.int32))
        return tuple(ys) + tuple(lv_fin) + (n_steps,)

    results = _reg.invoke_fn(fn, lv_list, op_name="_while_loop")
    n_out = meta["n_out"]
    outs = results[:n_out]
    states = results[n_out:n_out + n_lv]
    if meta["out_single"]:
        outs = outs[0]
    if not isinstance(loop_vars, (list, tuple)):
        states = states[0]
    return outs, states


def cond(pred, then_func, else_func):
    """Run ``then_func()`` if ``pred`` else ``else_func()``.

    Parity: ``mx.nd.contrib.cond`` (ndarray/contrib.py:537).  With a
    concrete predicate only the taken branch executes (imperative
    reference semantics, fully taped); under a jax trace both branches
    are traced into one ``lax.cond``.
    """
    from ..ndarray.ndarray import NDArray

    p = pred.data() if isinstance(pred, NDArray) else jnp.asarray(pred)
    try:
        taken = bool(p)
    except jax.errors.TracerBoolConversionError:
        taken = None
    if taken is not None:
        return then_func() if taken else else_func()

    meta = {}

    def _branch(func):
        def run():
            with autograd.pause():
                out = func()
            single = not isinstance(out, (list, tuple))
            meta.setdefault("single", single)
            if meta["single"] != single:
                raise MXNetError("cond branches must return the same "
                                 "structure")
            return tuple(o.data() for o in _as_list(out))
        return run

    # branch bodies are traced INSIDE lax.cond, so only the taken branch
    # executes at runtime (and XLA never evaluates the untaken one)
    outs = lax.cond(p.reshape(()).astype(jnp.bool_),
                    _branch(then_func), _branch(else_func))
    wrapped = [NDArray(o) for o in outs]
    return wrapped[0] if meta["single"] else wrapped


# ---------------------------------------------------------------------------
# op-name registration: the reference registers control flow as invokable
# OPERATORS (`_foreach`/`_while_loop`/`_cond`, src/operator/control_flow.cc
# :475-531) whose subgraphs arrive as attributes.  Here the subgraphs are
# Python callables passed as attrs; dispatch runs through the imperative
# override hook because the bodies drive tracing themselves (a jitted
# wrapper cannot close over arbitrary Python control flow).
# ---------------------------------------------------------------------------


def _foreach_op_override(inputs, attrs, out):
    body = attrs.get("body")
    if not callable(body):
        raise MXNetError(
            "_foreach: pass body= (callable) — op-name form of "
            "nd.contrib.foreach")
    n_data = int(attrs.get("num_data", 1))
    data = list(inputs[:n_data])
    states = list(inputs[n_data:])
    if not data:
        raise MXNetError("_foreach: needs at least one data input")
    outs, final = foreach(body, data if len(data) != 1 else data[0],
                          states if len(states) != 1 else states[0])
    return tuple(_as_list(outs) + _as_list(final))


def _while_loop_op_override(inputs, attrs, out):
    cond_fn, func = attrs.get("cond"), attrs.get("func")
    if not (callable(cond_fn) and callable(func)):
        raise MXNetError(
            "_while_loop: pass cond= and func= callables — op-name form "
            "of nd.contrib.while_loop")
    outs, final = while_loop(
        cond_fn, func, list(inputs),
        max_iterations=int(attrs.get("max_iterations", 0)) or None)
    return tuple(_as_list(outs) + _as_list(final))


def _cond_op_override(inputs, attrs, out):
    pred, then_fn, else_fn = (attrs.get("cond"), attrs.get("then_func"),
                              attrs.get("else_func"))
    if not (callable(pred) and callable(then_fn) and callable(else_fn)):
        raise MXNetError(
            "_cond: pass cond=, then_func=, else_func= callables — "
            "op-name form of nd.contrib.cond")
    return tuple(_as_list(cond(pred(*inputs), lambda: then_fn(*inputs),
                               lambda: else_fn(*inputs))))


_reg.register("_foreach", num_outputs=-1)(lambda *a, **k: a)
_reg.register("_while_loop", num_outputs=-1)(lambda *a, **k: a)
_reg.register("_cond", num_outputs=-1)(lambda *a, **k: a)
_reg.register_invoke_override("_foreach", _foreach_op_override)
_reg.register_invoke_override("_while_loop", _while_loop_op_override)
_reg.register_invoke_override("_cond", _cond_op_override)
