"""Vision / detection operator wave: ROI pooling family, deformable
convolution, SSD MultiBox ops, RPN proposals, box codecs, bipartite
matching, SyncBatchNorm.

Parity targets (all under /root/reference/src/operator/):
``roi_pooling.cc``, ``contrib/roi_align.cc``, ``contrib/psroi_pooling.cc``,
``contrib/deformable_convolution.cc``,
``contrib/deformable_psroi_pooling.cc``, ``contrib/multibox_prior.cc``,
``contrib/multibox_target.cc``, ``contrib/multibox_detection.cc``,
``contrib/bounding_box.cc`` (box_encode/box_decode/bipartite_matching),
``contrib/proposal.cc``, ``contrib/multi_proposal.cc``,
``contrib/mrcnn_mask_target.cu``, ``contrib/sync_batch_norm.cc``.

TPU-native notes: every op is a fixed-shape XLA computation — ROI windows
become per-axis membership masks (two masked-max/sum contractions instead
of data-dependent slicing), sampling ops use gather-based bilinear
interpolation, and greedy argmax loops (bipartite matching, NMS inside
proposals) are ``lax.fori_loop``s with on-the-fly IoU rows so nothing
data-dependent changes a buffer shape.  Deformable conv samples per-tap
offset grids and contracts with the weight via one einsum (MXU-friendly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

_NEG = -1e30


def _take_batch(data, b):
    return jnp.take(data, b.astype(jnp.int32), axis=0)


def _axis_masks(start, end, size, bins):
    """(bins, size) membership masks for [start + i*bin, start+(i+1)*bin)."""
    i = jnp.arange(bins, dtype=jnp.float32)
    binw = (end - start) / bins
    lo = jnp.floor(start + i * binw)[:, None]
    hi = jnp.ceil(start + (i + 1) * binw)[:, None]
    pos = jnp.arange(size, dtype=jnp.float32)[None, :]
    return (pos >= lo) & (pos < hi)


@register("ROIPooling", aliases=("roi_pooling",))
def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max pooling over quantized ROI bins (reference: roi_pooling.cc)."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    n, c, h, w = data.shape

    def one(roi):
        img = _take_batch(data, roi[0])
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        hmask = _axis_masks(y1, jnp.maximum(y2 + 1, y1 + 1), h, ph)
        wmask = _axis_masks(x1, jnp.maximum(x2 + 1, x1 + 1), w, pw)
        t = jnp.where(hmask[:, None, :, None], img[None], _NEG).max(axis=2)
        out = jnp.where(wmask[:, None, None, :], t[None], _NEG).max(axis=3)
        out = out.transpose(2, 1, 0)  # (pw, ph, c) -> (c, ph, pw)
        return jnp.where(out <= _NEG / 2, 0.0, out)

    return jax.vmap(one)(rois.astype(jnp.float32)).astype(data.dtype)


def _roi_align_points(data_img, ys, xs):
    """Bilinear samples of (C, H, W) at float coords; zero outside."""
    c, h, w = data_img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def g(yi, xi):
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        return data_img[:, yc, xc]

    def inside(yi, xi):
        return ((yi >= -1) & (yi <= h) & (xi >= -1) & (xi <= w)).astype(
            data_img.dtype)

    val = (g(y0, x0) * ((1 - wy) * (1 - wx))
           + g(y0, x0 + 1) * ((1 - wy) * wx)
           + g(y0 + 1, x0) * (wy * (1 - wx))
           + g(y0 + 1, x0 + 1) * (wy * wx))
    return val * inside(ys, xs)


@register("_contrib_ROIAlign")
def _roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
               sample_ratio=-1, position_sensitive=False, aligned=False):
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    sr = int(sample_ratio) if int(sample_ratio) > 0 else 2
    n, c, h, w = data.shape
    off = 0.5 if aligned else 0.0

    def one(roi):
        img = _take_batch(data, roi[0])
        x1 = roi[1] * spatial_scale - off
        y1 = roi[2] * spatial_scale - off
        rw = jnp.maximum(roi[3] * spatial_scale - off - x1, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale - off - y1, 1.0)
        bh, bw = rh / ph, rw / pw
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        sy = jnp.arange(sr, dtype=jnp.float32)
        ys = y1 + (iy[:, None] + (sy[None, :] + 0.5) / sr) * bh  # (ph, sr)
        xs = x1 + (ix[:, None] + (sy[None, :] + 0.5) / sr) * bw  # (pw, sr)
        yy = jnp.broadcast_to(ys[:, None, :, None], (ph, pw, sr, sr))
        xx = jnp.broadcast_to(xs[None, :, None, :], (ph, pw, sr, sr))
        pts = _roi_align_points(img, yy.reshape(-1), xx.reshape(-1))
        pts = pts.reshape(c, ph, pw, sr * sr)
        out = pts.mean(axis=3)
        if position_sensitive:
            # channel (d, i, j) layout: c = (d * ph + i) * pw + j
            d = c // (ph * pw)
            out = out.reshape(d, ph, pw, ph, pw)
            out = out[:, jnp.arange(ph)[:, None], jnp.arange(pw)[None, :],
                      jnp.arange(ph)[:, None], jnp.arange(pw)[None, :]]
        return out

    return jax.vmap(one)(rois.astype(jnp.float32)).astype(data.dtype)


@register("_contrib_PSROIPooling")
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=1,
                   pooled_size=1, group_size=0):
    """Position-sensitive average ROI pooling (reference:
    contrib/psroi_pooling.cc); channel (d, gi, gj) -> (d*gs + gi)*gs + gj."""
    ps = int(pooled_size)
    gs = int(group_size) if int(group_size) > 0 else ps
    od = int(output_dim)
    n, c, h, w = data.shape

    def one(roi):
        img = _take_batch(data, roi[0])
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        hmask = _axis_masks(y1, y1 + rh, h, ps)  # (ps, H)
        wmask = _axis_masks(x1, x1 + rw, w, ps)
        hm = hmask.astype(img.dtype)
        wm = wmask.astype(img.dtype)
        # sums[c, i, j] and counts[i, j]
        sums = jnp.einsum("ih,chw,jw->cij", hm, img, wm)
        cnt = jnp.maximum(jnp.einsum("ih,jw->ij", hm, wm), 1.0)
        avg = sums / cnt[None]
        # pick position-sensitive channel per (d, i, j)
        gi = (jnp.arange(ps) * gs) // ps
        gj = (jnp.arange(ps) * gs) // ps
        d = jnp.arange(od)
        chan = (d[:, None, None] * gs + gi[None, :, None]) * gs \
            + gj[None, None, :]
        return avg[chan, jnp.arange(ps)[None, :, None],
                   jnp.arange(ps)[None, None, :]]

    return jax.vmap(one)(rois.astype(jnp.float32)).astype(data.dtype)


@register("_contrib_DeformablePSROIPooling", num_outputs=2)
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=1, group_size=1, pooled_size=1,
                              part_size=0, sample_per_part=1, trans_std=0.0,
                              no_trans=False):
    """Deformable position-sensitive ROI pooling (reference:
    contrib/deformable_psroi_pooling.cc) via per-bin sampled averages."""
    ps = int(pooled_size)
    gs = int(group_size)
    od = int(output_dim)
    pt = int(part_size) if int(part_size) > 0 else ps
    sp = int(sample_per_part)
    n, c, h, w = data.shape

    def one(roi, tr):
        img = _take_batch(data, roi[0])
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale - 0.5
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ps, rw / ps
        iy = jnp.arange(ps, dtype=jnp.float32)
        ix = jnp.arange(ps, dtype=jnp.float32)
        if no_trans or tr is None:
            dy = jnp.zeros((ps, ps))
            dx = jnp.zeros((ps, ps))
        else:
            pi = ((iy * pt) // ps).astype(jnp.int32)
            pj = ((ix * pt) // ps).astype(jnp.int32)
            dy = tr[0][pi[:, None], pj[None, :]] * trans_std * rh
            dx = tr[1][pi[:, None], pj[None, :]] * trans_std * rw
        ss = (jnp.arange(sp, dtype=jnp.float32) + 0.5) / sp
        ys = (y1 + iy[:, None, None, None] * bh + dy[:, :, None, None]
              + ss[None, None, :, None] * bh)
        xs = (x1 + ix[None, :, None, None] * bw + dx[:, :, None, None]
              + ss[None, None, None, :] * bw)
        pts = _roi_align_points(img, ys.reshape(-1), xs.reshape(-1))
        avg = pts.reshape(c, ps, ps, sp * sp).mean(axis=3)
        gi = (jnp.arange(ps) * gs) // ps
        d = jnp.arange(od)
        chan = (d[:, None, None] * gs + gi[None, :, None]) * gs \
            + gi[None, None, :]
        return avg[chan, jnp.arange(ps)[None, :, None],
                   jnp.arange(ps)[None, None, :]]

    r = rois.astype(jnp.float32)
    if trans is None or no_trans:
        out = jax.vmap(lambda roi: one(roi, None))(r)
    else:
        out = jax.vmap(one)(r, trans.astype(jnp.float32))
    return out.astype(data.dtype), jnp.zeros_like(out)


def _zero_pad_sample(img, ys, xs):
    """Bilinear samples of (C, H, W) with zero padding outside: each corner
    outside the map contributes 0 (im2col zero-pad semantics, unlike the
    border-replicate of _roi_align_points)."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def g(yi, xi):
        ok = ((yi >= 0) & (yi <= h - 1) & (xi >= 0)
              & (xi <= w - 1)).astype(img.dtype)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        return img[:, yc, xc] * ok

    return (g(y0, x0) * ((1 - wy) * (1 - wx))
            + g(y0, x0 + 1) * ((1 - wy) * wx)
            + g(y0 + 1, x0) * (wy * (1 - wx))
            + g(y0 + 1, x0 + 1) * (wy * wx))


@register("_contrib_DeformableConvolution",
          inputs=("data", "offset", "weight", "bias"))
def _deformable_convolution(data, offset, weight, bias=None, kernel=(1, 1),
                            stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                            num_filter=1, num_group=1,
                            num_deformable_group=1, workspace=1024,
                            no_bias=False, layout=None):
    """Deformable conv v1 (reference: contrib/deformable_convolution.cc).
    Per-tap offset fields shift the sampling grid; sampled columns contract
    with the weight on the MXU via one einsum."""
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = (int(stride[0]), int(stride[1])) if stride else (1, 1)
    dh, dw = (int(dilate[0]), int(dilate[1])) if dilate else (1, 1)
    ph, pw = (int(pad[0]), int(pad[1])) if pad else (0, 0)
    n, c, h, w = data.shape
    ndg = int(num_deformable_group)
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    oy = jnp.arange(oh, dtype=jnp.float32) * sh - ph
    ox = jnp.arange(ow, dtype=jnp.float32) * sw - pw
    cols = []
    cpg = c // ndg  # channels per deformable group

    def sample_group(img_g, ys, xs):
        return _zero_pad_sample(img_g, ys.reshape(-1), xs.reshape(-1)) \
            .reshape(img_g.shape[0], oh, ow)

    for i in range(kh):
        for j in range(kw):
            t = i * kw + j
            taps = []
            for g in range(ndg):
                off_y = offset[:, (g * kh * kw + t) * 2]
                off_x = offset[:, (g * kh * kw + t) * 2 + 1]
                ys = oy[None, :, None] + i * dh + off_y
                xs = ox[None, None, :] + j * dw + off_x
                img_g = data[:, g * cpg:(g + 1) * cpg]
                taps.append(jax.vmap(sample_group)(img_g, ys, xs))
            cols.append(jnp.concatenate(taps, axis=1))  # (N, C, oh, ow)
    col = jnp.stack(cols, axis=2)  # (N, C, kh*kw, oh, ow)
    f = int(num_filter)
    ng = int(num_group)
    col = col.reshape(n, ng, c // ng, kh * kw, oh, ow)
    wgt = weight.reshape(ng, f // ng, c // ng, kh * kw)
    out = jnp.einsum("ngckhw,gfck->ngfhw",
                     col.reshape(n, ng, c // ng, kh * kw, oh, ow), wgt,
                     optimize=True)
    out = out.reshape(n, f, oh, ow)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ----------------------------------------------------------------------------
# SSD MultiBox ops
# ----------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior")
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps and steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps and steps[1] > 0 else 1.0 / w
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cyy, cxx = jnp.meshgrid(cy, cx, indexing="ij")  # (h, w)
    half = []
    r0 = ratios[0] ** 0.5 if ratios else 1.0
    for s in sizes:
        half.append((s * h / w * r0 / 2, s / r0 / 2))
    for r in ratios[1:]:
        rq = r ** 0.5
        half.append((sizes[0] * h / w * rq / 2, sizes[0] / rq / 2))
    hw = jnp.asarray(half, jnp.float32)  # (A, 2): (w_half, h_half)
    boxes = jnp.stack([
        cxx[..., None] - hw[None, None, :, 0],
        cyy[..., None] - hw[None, None, :, 1],
        cxx[..., None] + hw[None, None, :, 0],
        cyy[..., None] + hw[None, None, :, 1],
    ], axis=-1)  # (h, w, A, 4)
    out = boxes.reshape(1, -1, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _corner_to_center(b):
    aw = b[..., 2] - b[..., 0]
    ah = b[..., 3] - b[..., 1]
    ax = (b[..., 0] + b[..., 2]) / 2
    ay = (b[..., 1] + b[..., 3]) / 2
    return ax, ay, aw, ah


def _box_iou_single(a, b):
    """IoU between (A, 4) and (G, 4) corner boxes -> (A, G)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    aa = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    ab = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(aa[:, None] + ab[None, :] - inter, 1e-12)


@register("_contrib_MultiBoxTarget", num_outputs=3)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD target assignment (reference: contrib/multibox_target.cc):
    greedy per-gt best anchor, then per-anchor IoU threshold matching."""
    anchors = anchor.reshape(-1, 4)
    a = anchors.shape[0]
    g = label.shape[1]
    vx, vy, vw, vh = (float(v) for v in variances)

    def one(lab, pred):
        valid = lab[:, 0] >= 0  # (G,)
        gt = lab[:, 1:5]
        ious = _box_iou_single(anchors, gt)  # (A, G)
        ious = jnp.where(valid[None, :], ious, 0.0)

        # stage 1: each gt greedily claims its best remaining anchor
        def body(_, st):
            match, iou_m = st
            flat = jnp.argmax(iou_m)
            ai, gi = flat // g, flat % g
            ok = iou_m[ai, gi] > 1e-12
            match = jnp.where(ok, match.at[ai].set(gi.astype(jnp.int32)),
                              match)
            iou_m = jnp.where(ok, iou_m.at[ai, :].set(-1.0), iou_m)
            iou_m = jnp.where(ok, iou_m.at[:, gi].set(-1.0), iou_m)
            return match, iou_m

        match0 = jnp.full((a,), -1, jnp.int32)
        match, _ = lax.fori_loop(0, g, body, (match0, ious))
        # stage 2: unmatched anchors take any gt above the threshold
        best_gt = jnp.argmax(ious, axis=1).astype(jnp.int32)
        best_iou = jnp.max(ious, axis=1)
        match = jnp.where((match < 0) & (best_iou > overlap_threshold),
                          best_gt, match)
        matched = match >= 0
        mgt = gt[jnp.clip(match, 0, g - 1)]
        ax, ay, aw, ah = _corner_to_center(anchors)
        gx, gy, gw, gh = _corner_to_center(mgt)
        loc = jnp.stack([(gx - ax) / aw / vx, (gy - ay) / ah / vy,
                         jnp.log(jnp.maximum(gw / aw, 1e-12)) / vw,
                         jnp.log(jnp.maximum(gh / ah, 1e-12)) / vh], axis=1)
        loc = jnp.where(matched[:, None], loc, 0.0)
        mask = jnp.where(matched[:, None], 1.0, 0.0)
        mask = jnp.broadcast_to(mask, (a, 4))
        cls_t = jnp.where(matched,
                          lab[jnp.clip(match, 0, g - 1), 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # keep top-k hardest negatives (highest max non-background
            # prob), ignore the rest
            max_np = jnp.max(pred[1:, :], axis=0)  # (A,)
            neg_ok = (~matched) & (max_np > negative_mining_thresh)
            n_pos = jnp.sum(matched)
            k = jnp.maximum(n_pos * negative_mining_ratio,
                            float(minimum_negative_samples))
            score = jnp.where(neg_ok, max_np, -1.0)
            order = jnp.argsort(-score)
            rank = jnp.zeros((a,), jnp.int32).at[order].set(
                jnp.arange(a, dtype=jnp.int32))
            keep_neg = neg_ok & (rank < k)
            cls_t = jnp.where(matched, cls_t,
                              jnp.where(keep_neg, 0.0, float(ignore_label)))
        return loc.reshape(-1), mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label.astype(jnp.float32),
                                        cls_pred.astype(jnp.float32))
    return loc_t, loc_m, cls_t


@register("_contrib_MultiBoxDetection")
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0,
                        nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    from .contrib import _box_nms

    anchors = anchor.reshape(-1, 4)
    vx, vy, vw, vh = (float(v) for v in variances)
    ax, ay, aw, ah = _corner_to_center(anchors)

    def one(probs, locs):
        lp = locs.reshape(-1, 4)
        score = jnp.max(probs[1:, :], axis=0)
        cid = jnp.argmax(probs[1:, :], axis=0).astype(jnp.float32)
        cid = jnp.where(score < threshold, -1.0, cid)
        ox = lp[:, 0] * vx * aw + ax
        oy = lp[:, 1] * vy * ah + ay
        ow = jnp.exp(lp[:, 2] * vw) * aw / 2
        oh = jnp.exp(lp[:, 3] * vh) * ah / 2
        boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return jnp.concatenate([cid[:, None], score[:, None], boxes], axis=1)

    dets = jax.vmap(one)(cls_prob, loc_pred)  # (N, A, 6)
    out, _ = _box_nms(dets, overlap_thresh=nms_threshold, valid_thresh=0.0,
                      topk=nms_topk, coord_start=2, score_index=1,
                      id_index=0, force_suppress=force_suppress)
    return out


@register("_contrib_box_decode")
def _box_decode(data, anchors, std0=1.0, std1=1.0, std2=1.0, std3=1.0,
                clip=-1.0, format="corner"):
    a = anchors.reshape(-1, 4)
    if format == "corner":
        ax, ay, aw, ah = _corner_to_center(a)
    else:
        ax, ay, aw, ah = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    ox = data[..., 0] * std0 * aw + ax
    oy = data[..., 1] * std1 * ah + ay
    dw = data[..., 2] * std2
    dh = data[..., 3] * std3
    if clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    ow = jnp.exp(dw) * aw / 2
    oh = jnp.exp(dh) * ah / 2
    return jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)


@register("_contrib_box_encode", num_outputs=2)
def _box_encode(samples, matches, anchors, refs, means=None, stds=None):
    """Encode matched gt boxes as regression targets (gluon-cv parity)."""
    m = jnp.asarray(means if means is not None else (0., 0., 0., 0.),
                    jnp.float32)
    s = jnp.asarray(stds if stds is not None else (0.1, 0.1, 0.2, 0.2),
                    jnp.float32)

    def one(sample, match, anchor, ref):
        g = ref.shape[0]
        mref = ref[jnp.clip(match.astype(jnp.int32), 0, g - 1)]
        ax, ay, aw, ah = _corner_to_center(anchor)
        gx, gy, gw, gh = _corner_to_center(mref)
        t = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                       jnp.log(jnp.maximum(gw / aw, 1e-12)),
                       jnp.log(jnp.maximum(gh / ah, 1e-12))], axis=1)
        t = (t - m[None]) / s[None]
        mask = (sample > 0.5)[:, None]
        return jnp.where(mask, t, 0.0), jnp.broadcast_to(
            mask.astype(t.dtype), t.shape)

    return jax.vmap(one)(samples.astype(jnp.float32),
                         matches.astype(jnp.float32),
                         anchors.astype(jnp.float32),
                         refs.astype(jnp.float32))


@register("_contrib_bipartite_matching", num_outputs=2)
def _bipartite_matching(data, is_ascend=False, threshold=0.0, topk=-1):
    """Greedy bipartite matching on a (B, N, M) score matrix (reference:
    bounding_box-inl.h BipartiteMatchingForward)."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])
    b, n, m = flat.shape
    sign = 1.0 if not is_ascend else -1.0

    def one(scores):
        s = scores * sign  # maximize sm regardless of direction

        def body(_, st):
            row_m, col_m, sm, count = st
            flat_i = jnp.argmax(sm)
            ri, ci = flat_i // m, flat_i % m
            raw = sm[ri, ci] * sign
            # reference gate (bounding_box-inl.h:700): descending keeps
            # scores > threshold, ascending keeps scores < threshold
            ok = (raw > threshold) if not is_ascend else (raw < threshold)
            ok = ok & (sm[ri, ci] > _NEG / 2)
            if topk > 0:
                ok = ok & (count < topk)
            row_m = jnp.where(ok, row_m.at[ri].set(ci.astype(jnp.float32)),
                              row_m)
            col_m = jnp.where(ok, col_m.at[ci].set(ri.astype(jnp.float32)),
                              col_m)
            sm = jnp.where(ok, sm.at[ri, :].set(_NEG), sm)
            sm = jnp.where(ok, sm.at[:, ci].set(_NEG), sm)
            return row_m, col_m, sm, count + ok.astype(jnp.int32)

        row0 = jnp.full((n,), -1.0)
        col0 = jnp.full((m,), -1.0)
        row_m, col_m, _, _ = lax.fori_loop(
            0, min(n, m), body, (row0, col0, s, jnp.int32(0)))
        return row_m, col_m

    rows, cols = jax.vmap(one)(flat.astype(jnp.float32))
    return (rows.reshape(shape[:-1]),
            cols.reshape(shape[:-2] + (m,)))


# ----------------------------------------------------------------------------
# RPN proposals (reference: contrib/proposal.cc, multi_proposal.cc)
# ----------------------------------------------------------------------------


def _gen_base_anchors(scales, ratios, base_size):
    base = jnp.asarray([0, 0, base_size - 1, base_size - 1], jnp.float32)
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    for r in ratios:
        size = w * h
        ws = jnp.round(jnp.sqrt(size / r))
        hs = jnp.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                        cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return jnp.asarray(out, jnp.float32)  # (A, 4)


def _proposal_one(score, deltas, info, base_anchors, stride, pre_n, post_n,
                  nms_thresh, min_size):
    a, fh, fw = score.shape
    sy = jnp.arange(fh, dtype=jnp.float32) * stride
    sx = jnp.arange(fw, dtype=jnp.float32) * stride
    shift = jnp.stack(jnp.meshgrid(sx, sy, indexing="xy"), axis=-1)
    # anchor-major flat order matches the (A, fh, fw) score layout
    anchors = (base_anchors[:, None, None, :]
               + jnp.concatenate([shift, shift], -1)[None]).reshape(-1, 4)
    d = deltas.reshape(a, 4, fh, fw).transpose(0, 2, 3, 1).reshape(-1, 4)
    s = score.reshape(-1)
    ax, ay, aw, ah = _corner_to_center(anchors)
    aw, ah = aw + 1, ah + 1
    cx = d[:, 0] * aw + ax
    cy = d[:, 1] * ah + ay
    pw = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
    ph = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
    boxes = jnp.stack([cx - 0.5 * (pw - 1), cy - 0.5 * (ph - 1),
                       cx + 0.5 * (pw - 1), cy + 0.5 * (ph - 1)], axis=1)
    boxes = jnp.stack([
        jnp.clip(boxes[:, 0], 0, info[1] - 1),
        jnp.clip(boxes[:, 1], 0, info[0] - 1),
        jnp.clip(boxes[:, 2], 0, info[1] - 1),
        jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=1)
    ms = min_size * info[2]
    keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms)
            & (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
    s = jnp.where(keep, s, -1.0)
    order = jnp.argsort(-s)[:pre_n]
    boxes_k = boxes[order]
    s_k = s[order]

    def body(i, st):
        keep_m, = st
        box_i = lax.dynamic_slice_in_dim(boxes_k, i, 1, axis=0)
        iou = _box_iou_single(box_i, boxes_k)[0]
        sup = (iou > nms_thresh) & (jnp.arange(pre_n) > i) & keep_m[i]
        return (keep_m & ~sup,)

    (keep_m,) = lax.fori_loop(0, pre_n, body, (s_k > -1.0,))
    sc = jnp.where(keep_m, s_k, -1.0)
    order2 = jnp.argsort(-sc)[:post_n]
    return boxes_k[order2], jnp.maximum(sc[order2], 0.0)


def _proposal_impl(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                   rpn_post_nms_top_n, threshold, rpn_min_size, scales,
                   ratios, feature_stride):
    base = _gen_base_anchors(tuple(scales), tuple(ratios),
                             float(feature_stride))
    a = base.shape[0]
    fg = cls_prob[:, a:, :, :]  # foreground scores
    n = cls_prob.shape[0]
    pre_n = min(int(rpn_pre_nms_top_n),
                fg.shape[1] * fg.shape[2] * fg.shape[3])
    post_n = int(rpn_post_nms_top_n)
    boxes, scores = jax.vmap(
        lambda s, d, i: _proposal_one(s, d, i, base, float(feature_stride),
                                      pre_n, post_n, threshold,
                                      float(rpn_min_size)))(
        fg, bbox_pred, im_info)
    bidx = jnp.repeat(jnp.arange(n, dtype=jnp.float32), post_n)
    rois = jnp.concatenate([bidx[:, None],
                            boxes.reshape(-1, 4)], axis=1)
    return rois, scores.reshape(-1, 1)


@register("_contrib_Proposal", num_outputs=2)
def _proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
              rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
              scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
              feature_stride=16, output_score=False, iou_loss=False):
    return _proposal_impl(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                          rpn_post_nms_top_n, threshold, rpn_min_size,
                          scales, ratios, feature_stride)


@register("_contrib_MultiProposal", num_outputs=2)
def _multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                    rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                    scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                    feature_stride=16, output_score=False, iou_loss=False):
    return _proposal_impl(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                          rpn_post_nms_top_n, threshold, rpn_min_size,
                          scales, ratios, feature_stride)


@register("_contrib_mrcnn_mask_target", num_outputs=2)
def _mrcnn_mask_target(rois, gt_masks, matches, cls_targets,
                       num_rois=0, num_classes=1, mask_size=(14, 14),
                       sample_ratio=2, aligned=False):
    """Mask R-CNN training targets: crop+resize each matched gt mask to the
    roi (reference: contrib/mrcnn_mask_target.cu) via bilinear sampling."""
    ms_h, ms_w = (int(mask_size[0]), int(mask_size[1])) \
        if isinstance(mask_size, (tuple, list)) else (int(mask_size),) * 2
    nc = int(num_classes)

    def one_batch(roi_b, masks_b, match_b, cls_b):
        g = masks_b.shape[0]

        def one_roi(roi, match, cls):
            mask = jnp.take(masks_b, jnp.clip(match.astype(jnp.int32), 0,
                                              g - 1), axis=0)
            x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
            bh = jnp.maximum(y2 - y1, 1.0) / ms_h
            bw = jnp.maximum(x2 - x1, 1.0) / ms_w
            iy = jnp.arange(ms_h, dtype=jnp.float32)
            ix = jnp.arange(ms_w, dtype=jnp.float32)
            ys = y1 + (iy + 0.5) * bh
            xs = x1 + (ix + 0.5) * bw
            yy = jnp.broadcast_to(ys[:, None], (ms_h, ms_w))
            xx = jnp.broadcast_to(xs[None, :], (ms_h, ms_w))
            m = _roi_align_points(mask[None].astype(jnp.float32),
                                  yy.reshape(-1), xx.reshape(-1))
            m = m.reshape(ms_h, ms_w)
            cls_i = cls.astype(jnp.int32)
            tgt = jnp.zeros((nc, ms_h, ms_w), jnp.float32).at[
                jnp.clip(cls_i, 0, nc - 1)].set(m)
            wmask = jnp.zeros((nc, ms_h, ms_w), jnp.float32).at[
                jnp.clip(cls_i, 0, nc - 1)].set(
                jnp.where(cls_i > 0, 1.0, 0.0))
            return tgt, wmask

        return jax.vmap(one_roi)(roi_b, match_b, cls_b)

    t, w = jax.vmap(one_batch)(rois.astype(jnp.float32), gt_masks,
                               matches.astype(jnp.float32),
                               cls_targets.astype(jnp.float32))
    return t, w


# ----------------------------------------------------------------------------
# SyncBatchNorm — under GSPMD/shard_map the batch axis is global, so the
# single-program semantics ARE the synchronized semantics; the ndev/key
# attrs exist for API parity (reference: contrib/sync_batch_norm.cc).
# ----------------------------------------------------------------------------

@register("_contrib_SyncBatchNorm", needs_mode=True, num_outputs=3,
          inputs=("data", "gamma", "beta", "moving_mean", "moving_var"))
def _sync_batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                     momentum=0.9, fix_gamma=True, use_global_stats=False,
                     output_mean_var=False, ndev=1, key="", _mode="train"):
    from .nn import _batch_norm

    return _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=eps,
                       momentum=momentum, fix_gamma=fix_gamma,
                       use_global_stats=use_global_stats,
                       output_mean_var=output_mean_var, axis=1, _mode=_mode)


alias("_contrib_SparseEmbedding", "Embedding")


@register("_contrib_RROIAlign")
def _rroi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0,
                sampling_ratio=-1):
    """Rotated ROIAlign (reference: contrib/rroi_align.cc): rois are
    (batch, cx, cy, w, h, angle°); the sampling grid is rotated by angle."""
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    sr = int(sampling_ratio) if int(sampling_ratio) > 0 else 2

    def one(roi):
        img = _take_batch(data, roi[0])
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale, 1.0)
        theta = roi[5] * jnp.pi / 180.0
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        ss = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
        # local coords in [-0.5, 0.5] before rotation
        ly = ((iy[:, None] + ss[None, :]) / ph - 0.5) * rh  # (ph, sr)
        lx = ((ix[:, None] + ss[None, :]) / pw - 0.5) * rw  # (pw, sr)
        lyy = jnp.broadcast_to(ly[:, None, :, None], (ph, pw, sr, sr))
        lxx = jnp.broadcast_to(lx[None, :, None, :], (ph, pw, sr, sr))
        cosn, sinn = jnp.cos(theta), jnp.sin(theta)
        xs = cx + lxx * cosn - lyy * sinn
        ys = cy + lxx * sinn + lyy * cosn
        pts = _roi_align_points(img, ys.reshape(-1), xs.reshape(-1))
        return pts.reshape(img.shape[0], ph, pw, sr * sr).mean(axis=3)

    return jax.vmap(one)(rois.astype(jnp.float32)).astype(data.dtype)
