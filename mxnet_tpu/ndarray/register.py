"""Generate the ``mx.nd.*`` op namespace at import.

Reference: ``python/mxnet/ndarray/register.py:116-264`` — introspects the C op
registry (``MXSymbolListAtomicSymbolCreators``) and ``exec``-generates Python
wrappers.  Here the registry is in-process (``ops.registry``), so generation
is a plain closure per op: positional NDArray args + tensor kwargs are routed
to the op's declared input fields, everything else becomes static attrs.
"""
from __future__ import annotations

from ..ops import registry as _reg


def make_op_func(op_name):
    reg = _reg.get(op_name)

    def generic(*args, **kwargs):
        from .ndarray import NDArray

        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        if reg.variadic:
            inputs = [a for a in args if isinstance(a, NDArray)]
            attrs = kwargs
            fields = None
        else:
            n_in = len(reg.input_names)
            named = list(zip(reg.input_names, args[:n_in]))
            inputs = [a for _, a in named if a is not None]
            fields = [f for f, a in named if a is not None]
            for nm in reg.input_names[len(inputs):]:
                if nm in kwargs and isinstance(kwargs[nm], NDArray):
                    inputs.append(kwargs.pop(nm))
                    fields.append(nm)
            attrs = kwargs
            # excess positional args are attrs, in signature order
            # (e.g. transpose(x, (2, 0, 1)))
            extra = args[n_in:]
            if len(extra) > len(reg.attr_names):
                raise TypeError(
                    "%s takes at most %d positional arguments (%d given)"
                    % (op_name, n_in + len(reg.attr_names),
                       len(args)))
            for nm, val in zip(reg.attr_names, extra):
                if nm in attrs:
                    raise TypeError(
                        "%s got multiple values for argument %r"
                        % (op_name, nm))
                attrs[nm] = val
        return _reg.invoke(op_name, inputs, attrs, out=out,
                           fields=tuple(fields) if fields is not None else None)

    generic.__name__ = op_name
    generic.__doc__ = reg.doc
    return generic


def populate(namespace_dict, exclude_internal=False):
    """Install every registered op into a module namespace (mx.nd / mx.sym)."""
    for name in _reg.list_ops():
        public = name
        if name.startswith("_") and exclude_internal:
            continue
        namespace_dict.setdefault(public, make_op_func(name))
