"""``mx.nd.random`` namespace (parity: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from ..context import current_context
from ..ops import registry as _reg
from .ndarray import NDArray


def _run(name, shape, dtype, ctx, attrs, inputs=()):
    attrs = dict(attrs)
    if shape is not None:
        attrs["shape"] = tuple(shape) if isinstance(shape, (tuple, list)) else (shape,)
    if dtype is not None:
        attrs["dtype"] = dtype if isinstance(dtype, str) else str(dtype)
    with (ctx or current_context()):
        return _reg.invoke(name, list(inputs), attrs)


def uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", ctx=None, out=None,
            **kwargs):
    if isinstance(low, NDArray):
        return _reg.invoke("_sample_uniform", [low, high], {"shape": ()})
    return _run("_random_uniform", shape, dtype, ctx, {"low": low, "high": high})


def normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None,
           **kwargs):
    if isinstance(loc, NDArray):
        return _reg.invoke("_sample_normal", [loc, scale], {"shape": ()})
    return _run("_random_normal", shape, dtype, ctx, {"loc": loc, "scale": scale})


def randn(*shape, dtype="float32", ctx=None, **kwargs):
    return normal(0.0, 1.0, shape or (1,), dtype=dtype, ctx=ctx)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    if isinstance(alpha, NDArray):
        return _reg.invoke("_sample_gamma", [alpha, beta], {"shape": ()})
    return _run("_random_gamma", shape, dtype, ctx, {"alpha": alpha, "beta": beta})


def exponential(scale=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    return _run("_random_exponential", shape, dtype, ctx, {"lam": 1.0 / scale})


def poisson(lam=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    return _run("_random_poisson", shape, dtype, ctx, {"lam": lam})


def negative_binomial(k=1, p=1.0, shape=(1,), dtype="float32", ctx=None, out=None):
    return _run("_random_negative_binomial", shape, dtype, ctx, {"k": k, "p": p})


def randint(low, high, shape=(1,), dtype="int32", ctx=None, out=None):
    return _run("_random_randint", shape, dtype, ctx, {"low": low, "high": high})


def multinomial(data, shape=(), get_prob=False, dtype="int32"):
    return _reg.invoke("_sample_multinomial", [data],
                       {"shape": shape, "get_prob": get_prob, "dtype": dtype})


def shuffle(data):
    return _reg.invoke("_shuffle", [data])


def bernoulli(prob=0.5, shape=(1,), dtype="float32", ctx=None):
    return _run("_random_bernoulli", shape, dtype, ctx, {"prob": prob})
