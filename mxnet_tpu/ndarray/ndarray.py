"""NDArray: the tensor type, on PJRT buffers.

Reference: ``include/mxnet/ndarray.h:82`` + ``python/mxnet/ndarray/ndarray.py``
— a ref-counted Chunk holding a Storage handle plus an engine Var, with lazy
allocation and view semantics.

TPU-native: the chunk is a ``jax.Array`` (PJRT buffer) — already asynchronous
(dispatch returns futures), already pooled (PJRT allocator, reference
``src/storage/pooled_storage_manager.h`` has no work left to do).  MXNet-style
*mutation* (``a += b``, ``a[1:3] = x``, optimizer in-place updates) is
implemented as functional update + buffer swap, with the engine ``Var`` version
bumped so caches can observe writes.  Slicing returns copies, not aliasing
views: XLA buffers are immutable, so write-through views cannot exist — writes
must go through the base array (documented deviation; the test suites of the
reference never rely on write-through slices).
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, current_context
from ..engine import Engine, Var, _BulkRef
from ..telemetry import memdump as _memdump
from .. import autograd
from ..ops import registry as _reg

_DTYPE_ALIASES = {
    "float16": jnp.float16, "float32": jnp.float32, "float64": jnp.float64,
    "bfloat16": jnp.bfloat16, "uint8": jnp.uint8, "int8": jnp.int8,
    "int32": jnp.int32, "int64": jnp.int64, "bool": jnp.bool_,
}


def _to_jax_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _DTYPE_ALIASES.get(dtype, jnp.dtype(dtype))
    return jnp.dtype(dtype)


class NDArray:
    """A mutable-by-convention tensor over an immutable XLA buffer."""

    _op_result_cls = None  # resolved to NDArray below; mx.np overrides

    __slots__ = (
        "_data", "_pending", "_ctx", "_var",
        "_marked", "_grad", "_grad_req", "_grad_gen", "_fresh_grad",
        "_grad_owner", "_dlpack_mirror",
        "_tape_node", "_tape_index",
        "__weakref__",
    )

    def __init__(self, data, ctx=None, dtype=None):
        # a deferred bulk-segment output (engine._BulkRef) makes a LAZY
        # array: ``_data`` holds only the aval until the segment flushes
        pending = None
        if isinstance(data, _BulkRef):
            pending = data
        elif isinstance(data, NDArray):
            p = data._pending
            if p is not None:
                jdt0 = _to_jax_dtype(dtype)
                if jdt0 is None or jdt0 == p.aval.dtype:
                    pending = p  # share the promise; no forced flush
                else:
                    data = data.data()  # dtype change needs the value
            if pending is None:
                data = data._data
        if pending is not None:
            self._data = pending.aval  # ShapeDtypeStruct placeholder
            self._pending = pending
            self._init_rest(ctx)
            return
        jdt = _to_jax_dtype(dtype)
        if not isinstance(data, jax.Array):
            data = _np.asarray(data, dtype=jdt or None)
            if data.dtype == _np.float64 and jdt is None:
                data = data.astype(_np.float32)
            ctx = ctx if ctx is not None else current_context()
            data = jax.device_put(data, ctx.jax_device)
            # a host->device upload is a real allocation (params, data
            # batches) — attribute it; op results (already jax.Array)
            # churn too fast to tag and count as "temp" in the sweep
            _memdump.tag(data)
        elif jdt is not None and data.dtype != jdt:
            data = data.astype(jdt)
        self._data = data
        self._pending = None
        self._init_rest(ctx)

    def _init_rest(self, ctx):
        self._ctx = ctx if ctx is not None else current_context()
        self._var = Var()
        self._marked = False
        self._grad = None
        self._grad_owner = None
        self._dlpack_mirror = None
        self._grad_req = "write"
        self._grad_gen = -1
        self._fresh_grad = False
        self._tape_node = None
        self._tape_index = 0

    # ------------------------------------------------------------------
    # core accessors
    # ------------------------------------------------------------------
    def data(self):
        """The raw jax.Array (framework-internal)."""
        if self._pending is not None:
            self._materialize()
        if self._dlpack_mirror is not None:
            self._sync_dlpack_write()
        return self._data

    def _materialize(self):
        """Resolve a deferred bulk-segment output into a concrete buffer.

        Reading a lazy array is a sync point: the open segment flushes
        (one fused push) and the promised value lands here.  If the flush
        failed, the first reader gets the original exception (propagated
        from flush / rethrown off this var) and the value is gone for good.
        """
        p = self._pending
        if p.value is None and not p.failed:
            p.segment.flush("data")
        if p.value is None:
            self._var.rethrow()
            raise MXNetError(
                "deferred NDArray lost: the bulk segment computing it "
                "failed (the original error was raised at the first read)")
        self._data = p.value
        self._pending = None

    def _set_data(self, new_data):
        """In-place write: swap buffer + bump the engine var version."""
        old = self._data
        self._data = new_data
        self._pending = None  # an overwrite supersedes any deferred value
        self._var.on_write()
        # grad-view write-through: reference .grad is the ACTUAL shared
        # NDArray, so mutating it mutates the stored gradient.  Our wrapper
        # is fresh per access (immutable buffers), so propagate writes back
        # to the owning array's gradient slot — but only while the view is
        # current (a later backward() orphans old views instead of letting
        # their read-modify-writes clobber the newer gradient).
        owner = self._grad_owner
        if owner is not None and owner._grad is old:
            owner._grad = new_data

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def sharding(self):
        """The jax sharding of the backing buffer (SingleDeviceSharding
        for plain arrays, NamedSharding after ``nd.shard``/``reshard``).

        Reading a lazy (bulk-deferred) array's sharding is a sync point:
        the open segment flushes so the concrete buffer can answer.
        """
        self._var.rethrow()
        return self.data().sharding

    @property
    def _in_graph(self):
        return self._marked or self._tape_node is not None

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self):
        self._var.rethrow()
        Engine.get().notify_sync("wait_to_read")
        self.data().block_until_ready()
        return self

    def asnumpy(self):
        self._var.rethrow()
        Engine.get().notify_sync("asnumpy")
        return _np.asarray(self.data())

    def __array__(self, dtype=None, copy=None):
        # numpy protocol: without this np.asarray() would fall back to
        # element-wise __getitem__ iteration (one device gather per scalar)
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def asscalar(self):
        if self.size != 1:
            raise ValueError("the array is not scalar-sized")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            _np.asarray(self.data()), "x".join(map(str, self.shape)),
            self._ctx)

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Mark for gradient collection (parity: ndarray.py attach_grad)."""
        self._marked = True
        self._grad_req = grad_req
        self._grad = jnp.zeros(self.shape, self.dtype) if grad_req != "null" else None
        if self._grad is not None:
            _memdump.tag(self._grad, origin="grad")

    @property
    def grad(self):
        if self._grad is None:
            return None
        from .sparse import BaseSparseNDArray

        if isinstance(self._grad, BaseSparseNDArray):
            return self._grad
        out = NDArray(self._grad, ctx=self._ctx)
        out._grad_owner = self
        return out

    def _accumulate_grad(self, ct):
        # MXNet 'write' semantics: a new backward pass overwrites .grad, but
        # multiple contributions WITHIN one pass sum.  The pass generation
        # counter (autograd._backward_gen) distinguishes the two cases.
        if self._grad_req == "null":
            return
        from .sparse import BaseSparseNDArray, RowSparseNDArray

        gen = autograd.current_backward_gen()
        fresh = self._grad_gen != gen
        self._grad_gen = gen
        self._fresh_grad = True
        if isinstance(ct, BaseSparseNDArray):
            # row_sparse gradient (sparse Embedding path): keep it sparse
            prev = self._grad
            if prev is None or (fresh and self._grad_req == "write"):
                self._grad = ct
            elif isinstance(prev, RowSparseNDArray):
                self._grad = prev + ct
            else:
                self._grad = ct.scatter_add_into(prev)
            return
        ct = ct.astype(self.dtype)
        if isinstance(self._grad, BaseSparseNDArray):
            prev = self._grad.tostype("default").data() \
                if not (fresh and self._grad_req == "write") else None
            self._grad = ct if prev is None else prev + ct
            return
        if self._grad is None or (fresh and self._grad_req == "write"):
            self._grad = ct
        else:
            self._grad = self._grad + ct
        # re-attribute: accumulation replaced the buffer attach_grad
        # tagged (no-op for deferred/sparse values — tag() only takes
        # concrete jax.Arrays, and backward flushes before returning)
        _memdump.tag(self._grad, origin="grad")

    def zero_grad(self):
        if self._grad is not None:
            self._grad = jnp.zeros(self.shape, self.dtype)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def detach(self):
        # passing the NDArray (not its buffer) keeps a deferred value lazy
        out = NDArray(self, ctx=self._ctx)
        return out

    # ------------------------------------------------------------------
    # conversion / copies
    # ------------------------------------------------------------------
    def astype(self, dtype, copy=True):
        jdt = _to_jax_dtype(dtype)
        if not copy and self.dtype == jdt:
            return self
        return _reg.invoke("cast", [self], {"dtype": _np.dtype(jdt).name})

    def copy(self):
        return NDArray(self, ctx=self._ctx)

    def copyto(self, other):
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise ValueError("copyto shape mismatch")
            other._set_data(
                jax.device_put(self.data(), other._ctx.jax_device).astype(other.dtype))
            return other
        if isinstance(other, Context):
            return self.as_in_context(other)
        raise TypeError("copyto target must be NDArray or Context")

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        out = NDArray(jax.device_put(self.data(), context.jax_device), ctx=context)
        out._tape_node = self._tape_node
        out._tape_index = self._tape_index
        return out

    def as_in_ctx(self, context):
        return self.as_in_context(context)

    def reshard(self, spec=None, mesh=None):
        """In-place redistribute onto ``mesh`` per ``spec`` (async push).

        The data movement is ``jax.device_put`` pushed through the
        engine like any op — dispatch returns immediately and the swap
        publishes a future-backed buffer.  ``mesh`` defaults to the
        ambient mesh (``with Mesh(...):`` / ``mx.tpu(mesh=...)``).
        Counted by ``mxnet_reshard_total{axis}`` — resharding in a hot
        loop is an mxlint finding (SH902).
        """
        if autograd.is_recording() and self._in_graph:
            # in-place placement swap on a taped array would invalidate
            # the recorded primals; use nd.shard() for a taped copy
            raise MXNetError("reshard on a taped array; use nd.shard()")
        from .. import sharding as _sharding

        sh = _sharding.named_sharding(mesh, spec)
        _sharding.maybe_verify(sh.mesh, sh.spec, shape=self.shape,
                               what="reshard")
        data = self.data()
        eng = Engine.get()
        new = eng.push(lambda: jax.device_put(data, sh),
                       read_vars=(self._var,), op_name="reshard")
        eng.track(new)
        _sharding.record_reshard(sh.spec, data.nbytes, origin="reshard")
        self._set_data(new)
        return self

    def with_sharding_constraint(self, spec=None, mesh=None):
        """Pin this array's partitioning through a recorded op — the
        traceable form of :func:`shard` (usable under autograd,
        ``hybridize`` and inside bulk segments; under jit it lowers to
        the GSPMD annotation rather than a data movement)."""
        from .. import sharding as _sharding

        sh = _sharding.named_sharding(mesh, spec)
        _sharding.maybe_verify(sh.mesh, sh.spec, shape=self.shape,
                               what="with_sharding_constraint")
        return _reg.invoke("_sharding_constraint", [self], {"sharding": sh})

    def as_nd_ndarray(self):
        return self

    # ------------------------------------------------------------------
    # DLPack interchange (reference ndarray.py:2825-2893 to_dlpack_for_read/
    # to_dlpack_for_write/from_dlpack).  Zero-copy when the PJRT backend
    # exports external references (CPU; real TPU buffers); the axon tunnel
    # plugin does not, so export falls back to a host copy there.
    # ------------------------------------------------------------------
    def _dlpack_source(self):
        """The object whose ``__dlpack__`` we export: the device buffer when
        the backend supports external references, else a host copy."""
        self._var.rethrow()
        if self._pending is not None:
            self._materialize()
        if self._dlpack_mirror is not None:
            return self._dlpack_mirror
        try:
            self._data.__dlpack_device__()
            return self._data
        except Exception:  # PJRT_Buffer_*ExternalReference unimplemented
            # np.array (not asarray): device_get hands back a READONLY host
            # view, which numpy refuses to export over DLPack
            return _np.array(self._data)

    def __dlpack__(self, **kwargs):
        return self._dlpack_source().__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._dlpack_source().__dlpack_device__()

    def to_dlpack_for_read(self):
        """Legacy-capsule export; no writes allowed through the capsule."""
        return self._dlpack_source().__dlpack__()

    def to_dlpack_for_write(self):
        """Writable export: a host mirror that this array re-adopts at its
        next read sync point (``data()``/``asnumpy()``/``wait_to_read()``).

        XLA buffers are immutable, so the reference's write-through alias
        (engine WaitForWrite ordering) cannot exist; the documented
        TPU-native contract is: external writes through the capsule are
        visible after the next read-side sync, and the capsule must not be
        written after that.
        """
        self._var.rethrow()
        if self._pending is not None:
            self._materialize()
        if self._dlpack_mirror is None:
            self._dlpack_mirror = _np.array(self._data)  # writable host copy
        self._var.on_write()
        return self._dlpack_mirror.__dlpack__()

    def _sync_dlpack_write(self):
        m, self._dlpack_mirror = self._dlpack_mirror, None
        self._set_data(jax.device_put(m, self._ctx.jax_device))

    def tostype(self, stype):
        if stype != "default":
            from ..ndarray import sparse as _sp

            return _sp.dense_to(self, stype)
        return self

    # ------------------------------------------------------------------
    # shape ops (method forms)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape and "shape" in kwargs:
            shape = tuple(kwargs["shape"])
        return _reg.invoke("reshape", [self], {"shape": tuple(shape)})

    def reshape_like(self, other):
        return _reg.invoke("reshape_like", [self, other])

    def flatten(self):
        return _reg.invoke("flatten", [self])

    def expand_dims(self, axis):
        return _reg.invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _reg.invoke("squeeze", [self], {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _reg.invoke("transpose", [self], {"axes": axes or None})

    @property
    def T(self):
        return self.transpose()

    def swapaxes(self, dim1, dim2):
        return _reg.invoke("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def broadcast_to(self, shape):
        return _reg.invoke("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return _reg.invoke("broadcast_like", [self, other])

    def tile(self, reps):
        return _reg.invoke("tile", [self], {"reps": tuple(reps)})

    def slice(self, begin, end, step=None):
        return _reg.invoke("slice", [self],
                           {"begin": tuple(begin), "end": tuple(end),
                            "step": tuple(step) if step else ()})

    def slice_axis(self, axis, begin, end):
        return _reg.invoke("slice_axis", [self],
                           {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return _reg.invoke("take", [self, _as_nd(indices, self._ctx)],
                           {"axis": axis, "mode": mode})

    def one_hot(self, depth, **kwargs):
        kwargs["depth"] = depth
        return _reg.invoke("one_hot", [self], kwargs)

    # reductions as methods
    def sum(self, axis=None, keepdims=False):
        return _reg.invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _reg.invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return _reg.invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return _reg.invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return _reg.invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return _reg.invoke("norm", [self],
                           {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return _reg.invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _reg.invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def clip(self, a_min, a_max):
        return _reg.invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return _reg.invoke("abs", [self])

    def sign(self):
        return _reg.invoke("sign", [self])

    def sqrt(self):
        return _reg.invoke("sqrt", [self])

    def square(self):
        return _reg.invoke("square", [self])

    def exp(self):
        return _reg.invoke("exp", [self])

    def log(self):
        return _reg.invoke("log", [self])

    def relu(self):
        return _reg.invoke("relu", [self])

    def sigmoid(self):
        return _reg.invoke("sigmoid", [self])

    def tanh(self):
        return _reg.invoke("tanh", [self])

    def softmax(self, axis=-1):
        return _reg.invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return _reg.invoke("log_softmax", [self], {"axis": axis})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return _reg.invoke("dot", [self, other],
                           {"transpose_a": transpose_a, "transpose_b": transpose_b})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return _reg.invoke("topk", [self],
                           {"axis": axis, "k": k, "ret_typ": ret_typ,
                            "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return _reg.invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def argsort(self, axis=-1, is_ascend=True):
        return _reg.invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def flip(self, axis):
        return _reg.invoke("reverse", [self], {"axis": axis})

    def pad(self, mode, pad_width, constant_value=0.0):
        return _reg.invoke("pad", [self],
                           {"mode": mode, "pad_width": tuple(pad_width),
                            "constant_value": constant_value})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return _reg.invoke("split", [self],
                           {"num_outputs": num_outputs, "axis": axis,
                            "squeeze_axis": squeeze_axis})

    # ------------------------------------------------------------------
    # arithmetic operators
    # ------------------------------------------------------------------
    def _binary(self, other, op, scalar_op, rscalar_op=None, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return _reg.invoke(op, [a, b])
        if isinstance(other, (int, float, bool, _np.number)):
            name = (rscalar_op or scalar_op) if reverse else scalar_op
            return _reg.invoke(name, [self], {"scalar": float(other)})
        if isinstance(other, (_np.ndarray, list, tuple)):
            return self._binary(NDArray(other, ctx=self._ctx), op, scalar_op,
                                rscalar_op, reverse)
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    def __radd__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar", reverse=True)

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar", "_rminus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar", "_rminus_scalar",
                            reverse=True)

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar", reverse=True)

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar", "_rdiv_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar", "_rdiv_scalar",
                            reverse=True)

    def __mod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar", "_rmod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "broadcast_mod", "_mod_scalar", "_rmod_scalar",
                            reverse=True)

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar", "_rpower_scalar")

    def __rpow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar", "_rpower_scalar",
                            reverse=True)

    def __neg__(self):
        return _reg.invoke("negative", [self])

    def __abs__(self):
        return _reg.invoke("abs", [self])

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    __hash__ = None  # mutable; matches reference NDArray unhashability

    # in-place (functional under the hood; tape-aware like reference += )
    def __iadd__(self, o):
        res = self.__add__(o)
        self._adopt(res)
        return self

    def __isub__(self, o):
        res = self.__sub__(o)
        self._adopt(res)
        return self

    def __imul__(self, o):
        res = self.__mul__(o)
        self._adopt(res)
        return self

    def __itruediv__(self, o):
        res = self.__truediv__(o)
        self._adopt(res)
        return self

    def _adopt(self, res):
        p = res._pending
        if p is not None and self._grad_owner is None \
                and self._dlpack_mirror is None:
            # adopt the promise itself: the in-place write stays deferred
            # but its version bump happens NOW, exactly when eager would
            self._data = p.aval  # ShapeDtypeStruct placeholder
            self._pending = p
            self._var.on_write()
        else:
            self._set_data(res.data())
        self._tape_node = res._tape_node
        self._tape_index = res._tape_index

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _conv_index(self, key):
        if isinstance(key, NDArray):
            return self._data_index(key)
        if isinstance(key, tuple):
            return tuple(self._data_index(k) if isinstance(k, NDArray) else k
                         for k in key)
        return key

    @staticmethod
    def _data_index(k):
        d = k.data()
        if jnp.issubdtype(d.dtype, jnp.floating):
            d = d.astype(jnp.int32)
        return d

    def __getitem__(self, key):
        key = self._conv_index(key)
        if autograd.is_recording() and self._in_graph:
            # route through a recorded op so indexing stays differentiable
            # (reference supports basic-index reads under autograd;
            # index arrays are gather constants — no grad w.r.t. them)
            from ..ops.registry import invoke_fn

            (out,) = invoke_fn(lambda d: (d[key],), [self],
                               op_name="_index")
            return out
        return NDArray(self.data()[key], ctx=self._ctx)

    def __setitem__(self, key, value):
        if autograd.is_recording() and self._in_graph:
            raise MXNetError("in-place assignment on a taped array")
        key = self._conv_index(key)
        if isinstance(value, NDArray):
            value = value.data()
        elif not isinstance(value, jax.Array):
            value = _np.asarray(value)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype),
                                   self.shape)
        else:
            new = self.data().at[key].set(jnp.asarray(value, dtype=self.dtype))
        self._set_data(jnp.asarray(new, dtype=self.dtype))

    # ------------------------------------------------------------------
    # serialization handled in ndarray.utils (save/load)
    # ------------------------------------------------------------------


NDArray._op_result_cls = NDArray


class _CapsuleHolder:
    """Adapts a legacy DLPack capsule to the array-protocol consumers
    (np.from_dlpack / jax.dlpack.from_dlpack want ``__dlpack__`` objects)."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU; legacy capsules carry no device metadata


def from_dlpack(obj):
    """Build an NDArray from a DLPack-capable object or legacy capsule.

    Parity: reference ``ndarray.py:2878-2893`` (``from_dlpack``).  Zero-copy
    where the producing/consuming backends share memory space (CPU);
    otherwise the backend copies on import.
    """
    if isinstance(obj, NDArray):
        return obj
    if not hasattr(obj, "__dlpack__"):
        obj = _CapsuleHolder(obj)  # legacy PyCapsule form
    try:
        data = jax.dlpack.from_dlpack(obj)
    except Exception:
        data = jnp.asarray(_np.from_dlpack(obj))
    return NDArray(data)


def to_dlpack_for_read(data):
    """Module-level form of ``NDArray.to_dlpack_for_read`` (reference
    ``ndarray.py:2825``)."""
    return data.to_dlpack_for_read()


def to_dlpack_for_write(data):
    """Module-level form of ``NDArray.to_dlpack_for_write`` (reference
    ``ndarray.py:2851``)."""
    return data.to_dlpack_for_write()


def _as_nd(x, ctx=None):
    if isinstance(x, NDArray):
        return x
    return NDArray(x, ctx=ctx)


def shard(arr, spec=None, mesh=None):
    """A copy of ``arr`` distributed onto ``mesh`` per ``spec``.

    ``mesh`` defaults to the ambient mesh (``with Mesh(...):`` or
    ``mx.tpu(mesh=...)``); ``spec=None`` replicates.  The movement is a
    ``jax.device_put`` pushed through the engine — async like any op.
    Under autograd recording the put is routed through a recorded op
    (``device_put`` is differentiable: gradients reshard back), so a
    sharded forward stays on the tape.
    """
    from .. import sharding as _sharding

    arr = _as_nd(arr)
    sh = _sharding.named_sharding(mesh, spec)
    _sharding.maybe_verify(sh.mesh, sh.spec, shape=arr.shape, what="shard")
    _sharding.record_reshard(sh.spec, arr.dtype.itemsize * arr.size,
                             origin="shard")
    if autograd.is_recording() and arr._in_graph:
        from ..ops.registry import invoke_fn

        (out,) = invoke_fn(lambda d: (jax.device_put(d, sh),), [arr],
                           op_name="_shard")
        return out
    data = arr.data()
    eng = Engine.get()
    new = eng.push(lambda: jax.device_put(data, sh),
                   read_vars=(arr._var,), op_name="shard")
    eng.track(new)
    out = NDArray(new, ctx=arr._ctx)
    return out


# ----------------------------------------------------------------------------
# creation helpers (parity: python/mxnet/ndarray/utils.py + ndarray.py)
# ----------------------------------------------------------------------------


def array(source_array, ctx=None, dtype=None):
    return NDArray(source_array, ctx=ctx, dtype=dtype)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    dtype = dtype or "float32"
    ctx = ctx or current_context()
    return NDArray(jnp.zeros(shape, _to_jax_dtype(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    dtype = dtype or "float32"
    ctx = ctx or current_context()
    return NDArray(jnp.ones(shape, _to_jax_dtype(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype=None):
    if isinstance(shape, int):
        shape = (shape,)
    dtype = dtype or "float32"
    ctx = ctx or current_context()
    return NDArray(jnp.full(shape, val, _to_jax_dtype(dtype)), ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    dtype = dtype or "float32"
    out = jnp.arange(start, stop, step, _to_jax_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx or current_context())


def zeros_like(a):
    return _reg.invoke("zeros_like", [a])


def ones_like(a):
    return _reg.invoke("ones_like", [a])


def concatenate(arrays, axis=0, always_copy=True):
    return _reg.invoke("concat", list(arrays), {"dim": axis})


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    axes.remove(source % tensor.ndim)
    axes.insert(destination % tensor.ndim, source % tensor.ndim)
    return tensor.transpose(axes)


def save(fname, data):
    """Save NDArray / list / dict of NDArrays (parity: MXNDArraySave).

    Format: the reference's binary ``.params`` container (versioned
    magic numbers, ``src/ndarray/ndarray.cc:1586-1860``) — files are
    interchangeable with reference MXNet in both directions.

    Atomic: bytes land in a same-directory temp file that is renamed
    over ``fname`` only once complete, so a preemption mid-write never
    corrupts an existing checkpoint (docs/fault_tolerance.md).
    """
    from ..base import atomic_path
    from . import legacy_io

    if isinstance(data, NDArray):
        arrays, names = [data.asnumpy()], []
    elif isinstance(data, (list, tuple)):
        arrays, names = [a.asnumpy() for a in data], []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k].asnumpy() for k in names]
    else:
        raise TypeError("unsupported save payload")
    with atomic_path(fname) as tmp:
        legacy_io.save_params(tmp, arrays, names)


def load(fname, ctx=None):
    """Load what :func:`save` (or reference MXNet) wrote.

    Accepts the reference binary container in all its versions (pre-V1
    through V3) and, for back-compat with earlier snapshots of this
    framework, the .npz container it used to write.
    """
    from . import legacy_io

    if legacy_io.is_legacy_file(fname):
        arrays, names = legacy_io.load_params(fname)
        nds = [NDArray(a, ctx=ctx) if a is not None else None
               for a in arrays]
        if names:
            return dict(zip(names, nds))
        return nds
    with _np.load(fname, allow_pickle=False) as z:
        kind = str(z["__kind__"])
        if kind == "single":
            return NDArray(z["arr_0"], ctx=ctx)
        if kind == "list":
            n = len([k for k in z.files if k.startswith("arr_")])
            return [NDArray(z["arr_%d" % i], ctx=ctx) for i in range(n)]
        out = {}
        for k in z.files:
            if k.startswith("key:"):
                out[k[4:]] = NDArray(z[k], ctx=ctx)
        return out


def waitall():
    Engine.get().wait_for_all()
