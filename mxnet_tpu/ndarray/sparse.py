"""Sparse NDArrays: row_sparse + CSR.

Reference: ``include/mxnet/ndarray.h:61-65`` (three storage types) and
``python/mxnet/ndarray/sparse.py``.  XLA has no native sparse support
(SURVEY.md §7 hard-part 4), so these are *structured dense pairs*:

* ``RowSparseNDArray`` — (indices (K,), values (K, ...cols)) — the format the
  KVStore rowwise push/pull and sparse Embedding gradients use.  Ops that
  matter for the sparse training path (retain, sparse dot, conversion,
  sgd/adam sparse update via scatter) are implemented on the pair directly;
  everything else densifies explicitly via ``tostype('default')``.
* ``CSRNDArray`` — (indptr, indices, data) for 2-D matrices; dot with dense
  uses segment-sum (gather/scatter ride the VPU; fine for IO-bound workloads).
"""
from __future__ import annotations

import numpy as _np

import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, _to_jax_dtype


class BaseSparseNDArray:
    @property
    def _in_graph(self):
        return False


class RowSparseNDArray(BaseSparseNDArray):
    stype = "row_sparse"

    def __init__(self, data, indices, shape, ctx=None, canonical=False):
        self.values = data if isinstance(data, NDArray) else NDArray(data, ctx=ctx)
        self.indices = (indices if isinstance(indices, NDArray)
                        else NDArray(indices, ctx=ctx, dtype="int64"))
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        # canonical = indices known unique+sorted; lets hot paths skip the
        # host-synchronizing dedup in compact()
        self._canonical = canonical

    @classmethod
    def from_dense(cls, arr):
        """Compress a dense NDArray by dropping all-zero rows."""
        return row_sparse_array(arr)

    def compact(self):
        """Return an equivalent RowSparseNDArray with unique sorted indices
        (duplicate rows summed) — the canonical reference layout."""
        if self._canonical:
            return self
        idx = self.indices.asnumpy().astype(_np.int64)
        uniq, inv = _np.unique(idx, return_inverse=True)
        vals = self.values.data()
        summed = jnp.zeros((len(uniq),) + tuple(vals.shape[1:]), vals.dtype)
        summed = summed.at[jnp.asarray(inv)].add(vals)
        return RowSparseNDArray(NDArray(summed), NDArray(uniq), self._shape,
                                ctx=self._ctx, canonical=True)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            idx = jnp.concatenate([self.indices.data(),
                                   other.indices.data()])
            vals = jnp.concatenate([self.values.data(),
                                    other.values.data()])
            return RowSparseNDArray(NDArray(vals), NDArray(idx),
                                    self._shape, ctx=self._ctx).compact()
        return self.tostype("default") + other

    def scatter_add_into(self, dense_raw):
        """dense_raw.at[indices].add(values) — sparse apply."""
        return dense_raw.at[self.indices.data().astype(jnp.int32)].add(
            self.values.data().astype(dense_raw.dtype))

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def context(self):
        return self._ctx

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            dense = jnp.zeros(self._shape, self.values.dtype)
            dense = dense.at[self.indices.data().astype(jnp.int32)].set(
                self.values.data())
            return NDArray(dense, ctx=self._ctx)
        raise MXNetError("cannot convert row_sparse to %s" % stype)

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def copyto(self, other):
        return self.tostype("default").copyto(other)

    def __repr__(self):
        return "<RowSparseNDArray %s @%s>" % (
            "x".join(map(str, self._shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    stype = "csr"

    @classmethod
    def from_dense(cls, arr):
        return csr_matrix(arr)

    def __init__(self, data, indptr, indices, shape, ctx=None):
        self.data_arr = data if isinstance(data, NDArray) else NDArray(data, ctx=ctx)
        self.indptr = (indptr if isinstance(indptr, NDArray)
                       else NDArray(indptr, ctx=ctx, dtype="int64"))
        self.indices = (indices if isinstance(indices, NDArray)
                        else NDArray(indices, ctx=ctx, dtype="int64"))
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data_arr.dtype

    @property
    def context(self):
        return self._ctx

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            m, n = self._shape
            indptr = self.indptr.asnumpy().astype(_np.int64)
            indices = self.indices.asnumpy().astype(_np.int64)
            vals = self.data_arr.asnumpy()
            dense = _np.zeros((m, n), vals.dtype)
            for r in range(m):
                dense[r, indices[indptr[r]:indptr[r + 1]]] = vals[
                    indptr[r]:indptr[r + 1]]
            return NDArray(dense, ctx=self._ctx)
        raise MXNetError("cannot convert csr to %s" % stype)

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def __repr__(self):
        return "<CSRNDArray %s @%s>" % ("x".join(map(str, self._shape)), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(_np.asarray(data, dtype=dtype or "float32"),
                                _np.asarray(indices), shape, ctx=ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                        dtype=dtype or "float32")
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz.astype(_np.int64), dense.shape, ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(_np.asarray(data, dtype=dtype or "float32"),
                          _np.asarray(indptr), _np.asarray(indices), shape, ctx=ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                        dtype=dtype or "float32")
    m, n = dense.shape
    indptr = [0]
    indices = []
    vals = []
    for r in range(m):
        cols = _np.where(dense[r] != 0)[0]
        indices.extend(cols.tolist())
        vals.extend(dense[r, cols].tolist())
        indptr.append(len(indices))
    return CSRNDArray(_np.asarray(vals, dense.dtype), _np.asarray(indptr),
                      _np.asarray(indices), (m, n), ctx=ctx)


def dense_to(arr, stype):
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError("unknown stype %s" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    dt = dtype or "float32"
    if stype == "row_sparse":
        cols = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(_np.zeros((0,) + tuple(cols), dt),
                                _np.zeros((0,), "int64"), shape, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,), dt), _np.zeros((shape[0] + 1,), "int64"),
                          _np.zeros((0,), "int64"), shape, ctx=ctx)
    from .ndarray import zeros as dzeros

    return dzeros(shape, ctx=ctx, dtype=dt)


def retain(data, indices):
    """Keep only given rows of a RowSparseNDArray (parity: sparse_retain op)."""
    keep = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                       else indices).astype(_np.int64)
    cur_idx = data.indices.asnumpy()
    mask = _np.isin(cur_idx, keep)
    return RowSparseNDArray(NDArray(data.values.data()[_np.where(mask)[0]]),
                            cur_idx[mask], data.shape, ctx=data.context)


def cast_storage(arr, stype):
    """Convert between storage types (parity:
    ``src/operator/tensor/cast_storage.cc``).

    ``default`` ↔ ``row_sparse`` / ``csr`` in any direction (sparse →
    sparse routes through dense — same as the reference, which supports
    only default↔sparse pairs per cast).
    """
    cur = getattr(arr, "stype", "default")
    if cur == stype:
        return arr
    if cur != "default":
        arr = arr.tostype("default")
        if stype == "default":
            return arr
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        if len(arr.shape) != 2:
            raise MXNetError("cast_storage to csr needs a 2-D array")
        return csr_matrix(arr)
    raise MXNetError("cast_storage: unknown stype %r" % (stype,))


def _csr_to_coo_rows(csr):
    indptr = csr.indptr.asnumpy().astype(_np.int64)
    return _np.repeat(_np.arange(csr.shape[0]), _np.diff(indptr))


def _coo_to_csr(rows, cols, vals, shape, ctx):
    """Canonicalize COO (sorted, duplicates summed) into a CSRNDArray."""
    order = _np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], _np.asarray(vals)[order]
    if len(rows):
        key = rows * shape[1] + cols
        uniq, inv = _np.unique(key, return_inverse=True)
        summed = _np.zeros(len(uniq), vals.dtype)
        _np.add.at(summed, inv, vals)
        rows, cols, vals = uniq // shape[1], uniq % shape[1], summed
        nz = summed != 0
        rows, cols, vals = rows[nz], cols[nz], vals[nz]
    indptr = _np.zeros(shape[0] + 1, _np.int64)
    _np.add.at(indptr, rows + 1, 1)
    _np.cumsum(indptr, out=indptr)
    return CSRNDArray(vals, indptr, cols, shape, ctx=ctx)


def add(lhs, rhs):
    """Sparse-aware elementwise add (parity: ``elemwise_add`` sparse
    dispatch, ``src/operator/tensor/elemwise_binary_op_basic.cc``).

    csr+csr → csr and rsp+rsp → rsp keep the sparse storage; any mixed
    pairing falls back to dense, like the reference's FComputeEx table.
    The csr merge happens host-side (IO-scale data; the device path is
    dense).
    """
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError("sparse add: shape mismatch")
        rows = _np.concatenate([_csr_to_coo_rows(lhs),
                                _csr_to_coo_rows(rhs)])
        cols = _np.concatenate([lhs.indices.asnumpy(),
                                rhs.indices.asnumpy()]).astype(_np.int64)
        vals = _np.concatenate([lhs.data_arr.asnumpy(),
                                rhs.data_arr.asnumpy()])
        return _coo_to_csr(rows, cols, vals, lhs.shape, lhs.context)
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        return lhs + rhs  # already sparse-preserving (compacted)
    lhs = lhs.tostype("default") if hasattr(lhs, "tostype") else lhs
    rhs = rhs.tostype("default") if hasattr(rhs, "tostype") else rhs
    return lhs + rhs


def multiply(lhs, rhs):
    """Sparse-aware elementwise multiply.

    csr*csr / rsp*rsp intersect the nonzero patterns; sparse*scalar
    scales values in place (sparsity preserved — the reference's
    ``_mul_scalar`` sparse kernel); sparse*dense keeps the sparse
    operand's pattern (zeros stay zero).
    """
    import numbers

    if isinstance(lhs, numbers.Number):
        lhs, rhs = rhs, lhs
    elif not isinstance(lhs, BaseSparseNDArray) and \
            isinstance(rhs, BaseSparseNDArray):
        lhs, rhs = rhs, lhs  # commutative: sparse operand drives
    if isinstance(rhs, numbers.Number):
        if isinstance(lhs, RowSparseNDArray):
            return RowSparseNDArray(
                NDArray(lhs.values.data() * float(rhs)), lhs.indices,
                lhs.shape, ctx=lhs.context, canonical=lhs._canonical)
        if isinstance(lhs, CSRNDArray):
            return CSRNDArray(NDArray(lhs.data_arr.data() * float(rhs)),
                              lhs.indptr, lhs.indices, lhs.shape,
                              ctx=lhs.context)
        return lhs * rhs
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError("sparse multiply: shape mismatch")
        key_l = _csr_to_coo_rows(lhs) * lhs.shape[1] \
            + lhs.indices.asnumpy().astype(_np.int64)
        key_r = _csr_to_coo_rows(rhs) * rhs.shape[1] \
            + rhs.indices.asnumpy().astype(_np.int64)
        common, li, ri = _np.intersect1d(key_l, key_r,
                                         return_indices=True)
        vals = lhs.data_arr.asnumpy()[li] * rhs.data_arr.asnumpy()[ri]
        return _coo_to_csr(common // lhs.shape[1], common % lhs.shape[1],
                           vals, lhs.shape, lhs.context)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        # dense rhs sampled at the csr pattern: out keeps lhs's nonzeros
        rows = _csr_to_coo_rows(lhs)
        cols = lhs.indices.asnumpy().astype(_np.int64)
        picked = rhs.asnumpy()[rows, cols]
        return CSRNDArray(lhs.data_arr.asnumpy() * picked,
                          lhs.indptr.asnumpy(), cols, lhs.shape,
                          ctx=lhs.context)
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray):
        a, b = lhs.compact(), rhs.compact()
        common, ai, bi = _np.intersect1d(
            a.indices.asnumpy().astype(_np.int64),
            b.indices.asnumpy().astype(_np.int64), return_indices=True)
        vals = a.values.data()[jnp.asarray(ai)] \
            * b.values.data()[jnp.asarray(bi)]
        return RowSparseNDArray(NDArray(vals), common, lhs.shape,
                                ctx=lhs.context, canonical=True)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        picked = rhs.data()[lhs.indices.data().astype(jnp.int32)]
        return RowSparseNDArray(NDArray(lhs.values.data() * picked),
                                lhs.indices, lhs.shape, ctx=lhs.context,
                                canonical=lhs._canonical)
    return lhs.tostype("default") * (rhs.tostype("default")
                                     if hasattr(rhs, "tostype") else rhs)


def square_sum(data, axis=None, keepdims=False):
    """``sum(data ** 2)`` without densifying (parity:
    ``src/operator/tensor/square_sum.cc`` — the reference adds this op
    precisely because dense ``square`` + ``sum`` would materialize the
    full array; here it reduces over stored values only, since zero
    entries contribute nothing to a square-sum).

    Row_sparse with ``axis=1`` returns row_sparse (the reference's
    documented sparse-out case); everything else returns dense.
    """
    ax = tuple(axis) if isinstance(axis, (tuple, list)) else \
        (axis,) if axis is not None else None
    if isinstance(data, RowSparseNDArray):
        d = data.compact()
        vals = d.values.data()
        if ax == (1,) and len(data.shape) == 2:
            red = jnp.sum(jnp.square(vals), axis=1)
            if keepdims:
                red = red[:, None]
            out_shape = (data.shape[0], 1) if keepdims else (data.shape[0],)
            return RowSparseNDArray(NDArray(red), d.indices, out_shape,
                                    ctx=data.context, canonical=True)
        if ax is None:
            out = jnp.sum(jnp.square(vals))
            if keepdims:
                out = out.reshape((1,) * len(data.shape))
            return NDArray(out, ctx=data.context)
        if ax == (0,):
            # absent rows are zero, so summing stored rows is exact
            out = jnp.sum(jnp.square(vals), axis=0)
            if keepdims:
                out = out[None]
            return NDArray(out, ctx=data.context)
        raise MXNetError("square_sum: unsupported axis %r" % (axis,))
    if isinstance(data, CSRNDArray):
        vals = data.data_arr.data()
        if ax is None:
            out = jnp.sum(jnp.square(vals))
            if keepdims:
                out = out.reshape((1, 1))
            return NDArray(out, ctx=data.context)
        data = data.tostype("default")
    out = jnp.sum(jnp.square(data.data()), axis=ax, keepdims=keepdims)
    return NDArray(out, ctx=data.context)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """csr · dense without densifying the csr operand.

    Parity: ``src/operator/tensor/dot.cc`` sparse dot.  Each nonzero
    contributes ``data[k] * rhs[col[k]]`` to row ``row[k]`` — one gather
    plus one scatter-add, both VPU-friendly; the (static) row index per
    nonzero is computed host-side from indptr.
    """
    if isinstance(lhs, CSRNDArray):
        rhs_raw = rhs.data() if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        if transpose_b or rhs_raw.ndim != 2:
            # rare layouts take the dense fallback; the hot sparse path
            # below assumes a (N, K) rhs gathered by column index
            dense = lhs.tostype("default")
            return dense.dot(rhs, transpose_a=transpose_a,
                             transpose_b=transpose_b)
        indptr = lhs.indptr.asnumpy().astype(_np.int64)
        rows = _np.repeat(_np.arange(lhs.shape[0]), _np.diff(indptr))
        cols = lhs.indices.data().astype(jnp.int32)
        vals = lhs.data_arr.data()
        if transpose_a:
            # (N, M)·(M?, K): out[col] += v * rhs[row]
            contrib = vals[:, None] * rhs_raw[jnp.asarray(rows)]
            out = jnp.zeros((lhs.shape[1], rhs_raw.shape[1]), contrib.dtype)
            out = out.at[cols].add(contrib)
        else:
            contrib = vals[:, None] * rhs_raw[cols]
            out = jnp.zeros((lhs.shape[0], rhs_raw.shape[1]), contrib.dtype)
            out = out.at[jnp.asarray(rows)].add(contrib)
        return NDArray(out, ctx=lhs.context)
    if isinstance(lhs, RowSparseNDArray):
        lhs = lhs.tostype("default")
    return lhs.dot(rhs, transpose_a=transpose_a, transpose_b=transpose_b)
