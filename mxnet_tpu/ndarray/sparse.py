"""Sparse NDArrays: row_sparse + CSR.

Reference: ``include/mxnet/ndarray.h:61-65`` (three storage types) and
``python/mxnet/ndarray/sparse.py``.  XLA has no native sparse support
(SURVEY.md §7 hard-part 4), so these are *structured dense pairs*:

* ``RowSparseNDArray`` — (indices (K,), values (K, ...cols)) — the format the
  KVStore rowwise push/pull and sparse Embedding gradients use.  Ops that
  matter for the sparse training path (retain, sparse dot, conversion,
  sgd/adam sparse update via scatter) are implemented on the pair directly;
  everything else densifies explicitly via ``tostype('default')``.
* ``CSRNDArray`` — (indptr, indices, data) for 2-D matrices; dot with dense
  uses segment-sum (gather/scatter ride the VPU; fine for IO-bound workloads).
"""
from __future__ import annotations

import numpy as _np

import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, _to_jax_dtype


class BaseSparseNDArray:
    @property
    def _in_graph(self):
        return False


class RowSparseNDArray(BaseSparseNDArray):
    stype = "row_sparse"

    def __init__(self, data, indices, shape, ctx=None, canonical=False):
        self.values = data if isinstance(data, NDArray) else NDArray(data, ctx=ctx)
        self.indices = (indices if isinstance(indices, NDArray)
                        else NDArray(indices, ctx=ctx, dtype="int64"))
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()
        # canonical = indices known unique+sorted; lets hot paths skip the
        # host-synchronizing dedup in compact()
        self._canonical = canonical

    @classmethod
    def from_dense(cls, arr):
        """Compress a dense NDArray by dropping all-zero rows."""
        return row_sparse_array(arr)

    def compact(self):
        """Return an equivalent RowSparseNDArray with unique sorted indices
        (duplicate rows summed) — the canonical reference layout."""
        if self._canonical:
            return self
        idx = self.indices.asnumpy().astype(_np.int64)
        uniq, inv = _np.unique(idx, return_inverse=True)
        vals = self.values.data()
        summed = jnp.zeros((len(uniq),) + tuple(vals.shape[1:]), vals.dtype)
        summed = summed.at[jnp.asarray(inv)].add(vals)
        return RowSparseNDArray(NDArray(summed), NDArray(uniq), self._shape,
                                ctx=self._ctx, canonical=True)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            idx = jnp.concatenate([self.indices.data(),
                                   other.indices.data()])
            vals = jnp.concatenate([self.values.data(),
                                    other.values.data()])
            return RowSparseNDArray(NDArray(vals), NDArray(idx),
                                    self._shape, ctx=self._ctx).compact()
        return self.tostype("default") + other

    def scatter_add_into(self, dense_raw):
        """dense_raw.at[indices].add(values) — sparse apply."""
        return dense_raw.at[self.indices.data().astype(jnp.int32)].add(
            self.values.data().astype(dense_raw.dtype))

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def context(self):
        return self._ctx

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            dense = jnp.zeros(self._shape, self.values.dtype)
            dense = dense.at[self.indices.data().astype(jnp.int32)].set(
                self.values.data())
            return NDArray(dense, ctx=self._ctx)
        raise MXNetError("cannot convert row_sparse to %s" % stype)

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def copyto(self, other):
        return self.tostype("default").copyto(other)

    def __repr__(self):
        return "<RowSparseNDArray %s @%s>" % (
            "x".join(map(str, self._shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    stype = "csr"

    @classmethod
    def from_dense(cls, arr):
        return csr_matrix(arr)

    def __init__(self, data, indptr, indices, shape, ctx=None):
        self.data_arr = data if isinstance(data, NDArray) else NDArray(data, ctx=ctx)
        self.indptr = (indptr if isinstance(indptr, NDArray)
                       else NDArray(indptr, ctx=ctx, dtype="int64"))
        self.indices = (indices if isinstance(indices, NDArray)
                        else NDArray(indices, ctx=ctx, dtype="int64"))
        self._shape = tuple(shape)
        self._ctx = ctx or current_context()

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self.data_arr.dtype

    @property
    def context(self):
        return self._ctx

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            m, n = self._shape
            indptr = self.indptr.asnumpy().astype(_np.int64)
            indices = self.indices.asnumpy().astype(_np.int64)
            vals = self.data_arr.asnumpy()
            dense = _np.zeros((m, n), vals.dtype)
            for r in range(m):
                dense[r, indices[indptr[r]:indptr[r + 1]]] = vals[
                    indptr[r]:indptr[r + 1]]
            return NDArray(dense, ctx=self._ctx)
        raise MXNetError("cannot convert csr to %s" % stype)

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def __repr__(self):
        return "<CSRNDArray %s @%s>" % ("x".join(map(str, self._shape)), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        return RowSparseNDArray(_np.asarray(data, dtype=dtype or "float32"),
                                _np.asarray(indices), shape, ctx=ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                        dtype=dtype or "float32")
    nz = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
    return RowSparseNDArray(dense[nz], nz.astype(_np.int64), dense.shape, ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(_np.asarray(data, dtype=dtype or "float32"),
                          _np.asarray(indptr), _np.asarray(indices), shape, ctx=ctx)
    dense = _np.asarray(arg1.asnumpy() if isinstance(arg1, NDArray) else arg1,
                        dtype=dtype or "float32")
    m, n = dense.shape
    indptr = [0]
    indices = []
    vals = []
    for r in range(m):
        cols = _np.where(dense[r] != 0)[0]
        indices.extend(cols.tolist())
        vals.extend(dense[r, cols].tolist())
        indptr.append(len(indices))
    return CSRNDArray(_np.asarray(vals, dense.dtype), _np.asarray(indptr),
                      _np.asarray(indices), (m, n), ctx=ctx)


def dense_to(arr, stype):
    if stype == "row_sparse":
        return row_sparse_array(arr)
    if stype == "csr":
        return csr_matrix(arr)
    raise MXNetError("unknown stype %s" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    dt = dtype or "float32"
    if stype == "row_sparse":
        cols = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(_np.zeros((0,) + tuple(cols), dt),
                                _np.zeros((0,), "int64"), shape, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(_np.zeros((0,), dt), _np.zeros((shape[0] + 1,), "int64"),
                          _np.zeros((0,), "int64"), shape, ctx=ctx)
    from .ndarray import zeros as dzeros

    return dzeros(shape, ctx=ctx, dtype=dt)


def retain(data, indices):
    """Keep only given rows of a RowSparseNDArray (parity: sparse_retain op)."""
    keep = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                       else indices).astype(_np.int64)
    cur_idx = data.indices.asnumpy()
    mask = _np.isin(cur_idx, keep)
    return RowSparseNDArray(NDArray(data.values.data()[_np.where(mask)[0]]),
                            cur_idx[mask], data.shape, ctx=data.context)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """csr · dense without densifying the csr operand.

    Parity: ``src/operator/tensor/dot.cc`` sparse dot.  Each nonzero
    contributes ``data[k] * rhs[col[k]]`` to row ``row[k]`` — one gather
    plus one scatter-add, both VPU-friendly; the (static) row index per
    nonzero is computed host-side from indptr.
    """
    if isinstance(lhs, CSRNDArray):
        rhs_raw = rhs.data() if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        if transpose_b or rhs_raw.ndim != 2:
            # rare layouts take the dense fallback; the hot sparse path
            # below assumes a (N, K) rhs gathered by column index
            dense = lhs.tostype("default")
            return dense.dot(rhs, transpose_a=transpose_a,
                             transpose_b=transpose_b)
        indptr = lhs.indptr.asnumpy().astype(_np.int64)
        rows = _np.repeat(_np.arange(lhs.shape[0]), _np.diff(indptr))
        cols = lhs.indices.data().astype(jnp.int32)
        vals = lhs.data_arr.data()
        if transpose_a:
            # (N, M)·(M?, K): out[col] += v * rhs[row]
            contrib = vals[:, None] * rhs_raw[jnp.asarray(rows)]
            out = jnp.zeros((lhs.shape[1], rhs_raw.shape[1]), contrib.dtype)
            out = out.at[cols].add(contrib)
        else:
            contrib = vals[:, None] * rhs_raw[cols]
            out = jnp.zeros((lhs.shape[0], rhs_raw.shape[1]), contrib.dtype)
            out = out.at[jnp.asarray(rows)].add(contrib)
        return NDArray(out, ctx=lhs.context)
    if isinstance(lhs, RowSparseNDArray):
        lhs = lhs.tostype("default")
    return lhs.dot(rhs, transpose_a=transpose_a, transpose_b=transpose_b)
