"""Reference-compatible ``.params`` binary serialization.

Byte-for-byte implementation of the reference NDArray container format
(``src/ndarray/ndarray.cc:1586-1860``):

    uint64  0x112 (kMXAPINDArrayListMagic)      ndarray.cc:1829
    uint64  0 (reserved)
    uint64  n_arrays
      per array (NDArray::Save, ndarray.cc:1597):
        uint32  magic: 0xF993fac9 (V2) / 0xF993faca (V3, np-shape)
        int32   storage type (0 = default/dense)
        [sparse only] storage shape: int32 ndim + int64[ndim]
        shape:  int32 ndim + int64[ndim]            tuple.h:704
        ctx:    int32 dev_type, int32 dev_id        base.h:157
        int32   type flag (mshadow/base.h:307)
        raw C-order data bytes
        [sparse only] per aux: raw aux bytes
    uint64  n_names
      per name: uint64 length + bytes

Loading also accepts V1 (0xF993fac8) and the pre-V1 legacy layout where
the leading uint32 is the ndim itself (ndarray.cc LegacyLoad:1688),
so checkpoints from any reference version import directly.
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import MXNetError

_LIST_MAGIC = 0x112
_V1_MAGIC = 0xF993FAC8
_V2_MAGIC = 0xF993FAC9
_V3_MAGIC = 0xF993FACA

# mshadow/base.h:307 type flags
_FLAG2DTYPE = {
    0: _np.float32, 1: _np.float64, 2: _np.float16, 3: _np.uint8,
    4: _np.int32, 5: _np.int8, 6: _np.int64, 7: _np.bool_,
}
_DTYPE2FLAG = {_np.dtype(v): k for k, v in _FLAG2DTYPE.items()}
_DTYPE2FLAG[_np.dtype("bfloat16") if "bfloat16" in dir(_np) else
            _np.dtype(_np.float16)] = 2  # bf16 downcast on save


def _write_shape(out, shape):
    out.append(struct.pack("<i", len(shape)))
    out.append(struct.pack("<%dq" % len(shape), *shape))


def _save_one(arr):
    a = _np.ascontiguousarray(arr)
    if a.dtype not in _DTYPE2FLAG:
        if a.dtype == _np.dtype("float64"):
            pass
        elif str(a.dtype) == "bfloat16":
            a = a.astype(_np.float32)
        else:
            a = a.astype(_np.float32)
    flag = _DTYPE2FLAG.get(a.dtype, 0)
    out = [struct.pack("<I", _V2_MAGIC),
           struct.pack("<i", 0)]  # dense storage
    _write_shape(out, a.shape)
    out.append(struct.pack("<ii", 1, 0))  # ctx: cpu(0)
    out.append(struct.pack("<i", flag))
    out.append(a.tobytes())
    return b"".join(out)


class _Reader:
    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def read(self, n):
        if self.pos + n > len(self.buf):
            raise MXNetError("truncated .params file")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u32(self):
        return struct.unpack("<I", self.read(4))[0]

    def i32(self):
        return struct.unpack("<i", self.read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self.read(8))[0]


def _read_shape_i64(r):
    ndim = r.i32()
    return struct.unpack("<%dq" % ndim, r.read(8 * ndim)) if ndim else ()


def _load_one(r):
    magic = r.u32()
    if magic in (_V2_MAGIC, _V3_MAGIC):
        stype = r.i32()
        if stype != 0:
            raise MXNetError("sparse .params entries are not supported "
                             "on load; densify before saving")
        shape = _read_shape_i64(r)
    elif magic == _V1_MAGIC:
        shape = _read_shape_i64(r)
    else:
        # pre-V1: magic IS the ndim, dims are uint32
        ndim = magic
        if ndim > 32:
            raise MXNetError("corrupt .params entry (ndim=%d)" % ndim)
        shape = struct.unpack("<%dI" % ndim, r.read(4 * ndim)) \
            if ndim else ()
    if len(shape) == 0:
        return None  # is_none() array
    r.i32()  # dev_type
    r.i32()  # dev_id
    flag = r.i32()
    dtype = _FLAG2DTYPE.get(flag)
    if dtype is None:
        raise MXNetError("unknown dtype flag %d in .params" % flag)
    count = 1
    for s in shape:
        count *= s
    data = _np.frombuffer(r.read(count * _np.dtype(dtype).itemsize),
                          dtype=dtype).reshape(shape)
    return data.copy()


def save_params(fname, arrays, names):
    """Write the reference container (parity: MXNDArraySave)."""
    out = [struct.pack("<QQ", _LIST_MAGIC, 0),
           struct.pack("<Q", len(arrays))]
    for a in arrays:
        out.append(_save_one(a))
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        raw = n.encode("utf-8")
        out.append(struct.pack("<Q", len(raw)))
        out.append(raw)
    with open(fname, "wb") as f:
        f.write(b"".join(out))


def load_params(fname):
    """Read the reference container → (list of np arrays, list of names)."""
    with open(fname, "rb") as f:
        buf = f.read()
    r = _Reader(buf)
    header = r.u64()
    if header != _LIST_MAGIC:
        raise MXNetError("not a reference .params file (bad magic)")
    r.u64()  # reserved
    n = r.u64()
    arrays = [_load_one(r) for _ in range(n)]
    n_names = r.u64()
    names = []
    for _ in range(n_names):
        ln = r.u64()
        names.append(r.read(ln).decode("utf-8"))
    if names and len(names) != len(arrays):
        raise MXNetError("invalid .params file (name/array count)")
    return arrays, names


def is_legacy_file(fname):
    try:
        with open(fname, "rb") as f:
            head = f.read(8)
        return len(head) == 8 and \
            struct.unpack("<Q", head)[0] == _LIST_MAGIC
    except OSError:
        return False
