"""``mx.nd`` — imperative tensor namespace.

Op functions are generated at import from the live registry, mirroring the
reference's codegen-at-import (``python/mxnet/ndarray/register.py``).
"""
from __future__ import annotations

# ensure op modules register before namespace generation
from ..ops import tensor as _t  # noqa: F401
from ..ops import nn as _n  # noqa: F401
from ..ops import random_ops as _r  # noqa: F401
from ..ops import optimizer_ops as _o  # noqa: F401
from ..ops import contrib as _c  # noqa: F401
from ..ops import pallas_kernels as _p  # noqa: F401
from ..ops import paged_attention as _pa  # noqa: F401
from ..ops import misc as _m  # noqa: F401
from ..ops import vision as _v  # noqa: F401
from ..ops import quantized_ops as _q  # noqa: F401
from ..ops import npi as _npi  # noqa: F401
from ..ops import control_flow as _cf  # noqa: F401

from .ndarray import (  # noqa: F401
    NDArray, array, empty, zeros, ones, full, arange, zeros_like, ones_like,
    concatenate, moveaxis, save, load, waitall, shard,
    from_dlpack, to_dlpack_for_read, to_dlpack_for_write,
)
from . import random  # noqa: F401
from . import sparse  # noqa: F401
from .register import populate as _populate

_populate(globals())

# imperative cast_storage returns REAL sparse views (the registry op is
# the dense/graph rendering; parity: mx.nd.cast_storage returning
# CSRNDArray/RowSparseNDArray objects)
_graph_cast_storage = cast_storage  # noqa: F821  (registry-generated)


def cast_storage(data, stype="default"):  # noqa: F811
    if getattr(data, "stype", "default") != "default":
        return sparse.cast_storage(data, stype)
    if stype != "default" and not getattr(data, "_in_graph", False):
        # eager dense -> sparse view; in-graph (taped/jitted) arrays stay
        # on the registry op, whose dense rendering is differentiable
        return sparse.cast_storage(data, stype)
    return _graph_cast_storage(data, stype=stype)

# control-flow operators (lax.scan/while/cond lowering; ops/control_flow.py)
from ..ops.control_flow import (  # noqa: E402
    foreach as _contrib_foreach,
    while_loop as _contrib_while_loop,
    cond as _contrib_cond,
)

# user-defined ops (mx.operator registry; parity: mx.nd.Custom)
from ..operator import custom as Custom  # noqa: E402,F401

# contrib sub-namespace: ops named _contrib_* surface as nd.contrib.<name>
class _ContribNS:
    def __getattr__(self, item):
        fn = globals().get("_contrib_" + item)
        if fn is None:
            raise AttributeError("nd.contrib.%s" % item)
        return fn

    def __dir__(self):
        return sorted(n[len("_contrib_"):] for n in globals()
                      if n.startswith("_contrib_"))


contrib = _ContribNS()
