"""Binary operator extensions: load ops from standalone ``.so`` files.

Parity: the reference's ``lib_api.h`` + ``MXLoadLib``
(``include/mxnet/lib_api.h:527``, ``src/c_api/c_api.cc:105``) — custom
operators compiled with NO framework linkage, loaded at runtime and
registered into the operator registry under their own names.

TPU-native mechanism: the plugin's compute stays a host C function (the
ABI is dense f32 buffers, see ``src/plugin_api.h``); each loaded op is
registered as a JAX ``pure_callback`` so it composes with jit/vmap and
the tape.  Shape inference calls the plugin's ``infer_shape`` export at
trace time (shapes are static under XLA).  If the plugin exports a
backward, the op is wrapped in ``jax.custom_vjp`` and becomes
differentiable; otherwise gradients stop at it (documented, like
reference custom ops without a declared FGradient).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .base import MXNetError

_LOADED = {}

# ABI contract (MX_PLUGIN_MAX_RANK in src/plugin_api.h): plugins may not
# report more than this many output dims.  infer_shape validates the
# reported rank, which catches plugins that honor the buffer size but
# misreport out_ndim; a plugin that ignores the documented cap and writes
# past the buffer is undefined behavior like any other ABI violation.
_PLUGIN_MAX_RANK = 16


class _PluginOp:
    __slots__ = ("lib", "index", "name", "n_inputs", "has_backward")

    def __init__(self, lib, index):
        self.lib = lib
        self.index = index
        self.name = lib.mx_plugin_op_name(index).decode()
        self.n_inputs = int(lib.mx_plugin_op_num_inputs(index))
        self.has_backward = bool(lib.mx_plugin_op_has_backward(index))

    # -- ABI crossings ----------------------------------------------------
    def _shape_args(self, arrays):
        shapes = [np.asarray(a.shape, np.int64) for a in arrays]
        shape_ptrs = (ctypes.POINTER(ctypes.c_long) * len(arrays))(
            *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_long))
              for s in shapes])
        ndims = np.asarray([a.ndim for a in arrays], np.int32)
        return shapes, shape_ptrs, ndims

    def infer_shape(self, in_shapes):
        fake = [np.empty(s, np.float32) for s in in_shapes]
        _, shape_ptrs, ndims = self._shape_args(fake)
        out_shape = np.zeros(_PLUGIN_MAX_RANK, np.int64)
        out_ndim = ctypes.c_int(0)
        rc = self.lib.mx_plugin_op_infer_shape(
            self.index, shape_ptrs,
            ndims.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            len(fake),
            out_shape.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            ctypes.byref(out_ndim))
        if rc != 0:
            raise MXNetError("%s: infer_shape failed (%d)"
                             % (self.name, rc))
        if not 0 <= out_ndim.value <= _PLUGIN_MAX_RANK:
            raise MXNetError(
                "%s: plugin reported out_ndim=%d (max supported rank is %d; "
                "see plugin_api.h)" % (self.name, out_ndim.value,
                                       _PLUGIN_MAX_RANK))
        return tuple(int(d) for d in out_shape[:out_ndim.value])

    def forward_host(self, *arrays):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        shapes, shape_ptrs, ndims = self._shape_args(arrays)
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        out_shape = np.asarray(
            self.infer_shape([a.shape for a in arrays]), np.int64)
        out = np.empty(tuple(out_shape), np.float32)
        rc = self.lib.mx_plugin_op_forward(
            self.index, in_ptrs, shape_ptrs,
            ndims.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            len(arrays),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out_shape.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
            len(out_shape))
        if rc != 0:
            raise MXNetError("%s: forward failed (%d)" % (self.name, rc))
        return out

    def backward_host(self, out_grad, *arrays):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        og = np.ascontiguousarray(out_grad, np.float32)
        shapes, shape_ptrs, ndims = self._shape_args(arrays)
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        grads = [np.zeros(a.shape, np.float32) for a in arrays]
        grad_ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[g.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for g in grads])
        rc = self.lib.mx_plugin_op_backward(
            self.index, in_ptrs, shape_ptrs,
            ndims.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            len(arrays),
            og.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            grad_ptrs)
        if rc != 0:
            raise MXNetError("%s: backward failed (%d)" % (self.name, rc))
        return tuple(grads)


def _register(op):
    """Register one plugin op into the live registry as a pure_callback."""
    import jax
    import jax.numpy as jnp

    from .ops.registry import register

    def call_forward(*datas):
        out_shape = op.infer_shape([d.shape for d in datas])
        return jax.pure_callback(
            op.forward_host,
            jax.ShapeDtypeStruct(out_shape, jnp.float32),
            *datas, vmap_method="sequential")

    if op.has_backward:
        @jax.custom_vjp
        def fwd(*datas):
            return call_forward(*datas)

        def fwd_fwd(*datas):
            return call_forward(*datas), datas

        def fwd_bwd(datas, g):
            shapes = tuple(
                jax.ShapeDtypeStruct(d.shape, jnp.float32) for d in datas)
            return jax.pure_callback(
                op.backward_host, shapes, g, *datas,
                vmap_method="sequential")

        fwd.defvjp(fwd_fwd, fwd_bwd)
        body = fwd
    else:
        body = call_forward

    def forward(*datas):
        return body(*datas)

    forward.__name__ = op.name
    forward.__doc__ = ("Plugin op %r (binary extension, host compute via "
                       "the XLA callback bridge)." % op.name)
    register(op.name)(forward)


def load(path, verbose=False):
    """Load an operator plugin ``.so`` and register its ops.

    Parity: ``mx.library.load`` → ``MXLoadLib`` (c_api.cc:105).  Returns
    the list of op names registered.  Ops become visible as
    ``mx.nd.<name>`` / ``mx.sym.<name>`` immediately.
    """
    if path in _LOADED:
        return _LOADED[path]
    lib = ctypes.CDLL(path)
    lib.mx_plugin_abi_version.restype = ctypes.c_int
    if lib.mx_plugin_abi_version() != 1:
        raise MXNetError("%s: unsupported plugin ABI version" % path)
    lib.mx_plugin_num_ops.restype = ctypes.c_long
    lib.mx_plugin_op_name.restype = ctypes.c_char_p
    lib.mx_plugin_op_name.argtypes = [ctypes.c_long]
    lib.mx_plugin_op_num_inputs.restype = ctypes.c_long
    lib.mx_plugin_op_num_inputs.argtypes = [ctypes.c_long]
    lib.mx_plugin_op_has_backward.restype = ctypes.c_int
    lib.mx_plugin_op_has_backward.argtypes = [ctypes.c_long]
    PL = ctypes.POINTER(ctypes.c_long)
    PI = ctypes.POINTER(ctypes.c_int)
    PF = ctypes.POINTER(ctypes.c_float)
    PPL = ctypes.POINTER(PL)
    PPF = ctypes.POINTER(PF)
    lib.mx_plugin_op_infer_shape.restype = ctypes.c_int
    lib.mx_plugin_op_infer_shape.argtypes = [
        ctypes.c_long, PPL, PI, ctypes.c_long, PL, PI]
    lib.mx_plugin_op_forward.restype = ctypes.c_int
    lib.mx_plugin_op_forward.argtypes = [
        ctypes.c_long, PPF, PPL, PI, ctypes.c_long, PF, PL, ctypes.c_int]
    try:
        lib.mx_plugin_op_backward.restype = ctypes.c_int
        lib.mx_plugin_op_backward.argtypes = [
            ctypes.c_long, PPF, PPL, PI, ctypes.c_long, PF, PPF]
    except AttributeError:
        pass

    names = []
    for i in range(int(lib.mx_plugin_num_ops())):
        op = _PluginOp(lib, i)
        _register(op)
        names.append(op.name)
        if verbose:
            print("loaded plugin op %r (backward=%s)"
                  % (op.name, op.has_backward))
    # refresh the generated nd namespace so the new names resolve
    from . import ndarray as _nd_pkg
    from .ndarray.register import populate as _populate

    _populate(_nd_pkg.__dict__)
    _LOADED[path] = names
    return names
