"""RNN checkpoint helpers (parity: python/mxnet/rnn/rnn.py).

Cells' fused/unfused weight layouts differ; these helpers pack weights
through the cells before saving and unpack after loading so checkpoints
interchange between ``FusedRNNCell`` graphs and unfused stacks.
"""
from __future__ import annotations

from .. import model
from .rnn_cell import BaseRNNCell


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """Save symbol + packed params (parity: rnn.py:32)."""
    cells = _as_list(cells)
    for cell in cells:
        arg_params = cell.pack_weights(arg_params)
    model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load symbol + params, unpacking through the cells
    (parity: rnn.py:62)."""
    sym, arg, aux = model.load_checkpoint(prefix, epoch)
    cells = _as_list(cells)
    for cell in cells:
        arg = cell.unpack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback doing save_rnn_checkpoint
    (parity: rnn.py:97)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
