"""Legacy symbol-level RNN cell API (parity: python/mxnet/rnn/rnn_cell.py).

The pre-gluon recurrent surface: cells compose ``mx.sym`` graphs one
time step at a time (``cell(inputs, states)``), ``unroll`` builds the
whole sequence graph, ``FusedRNNCell`` maps onto the monolithic ``RNN``
operator (here a fused ``lax.scan`` chain — ops/nn.py:649 — instead of
cuDNN), and ``unpack_weights``/``pack_weights`` convert between the
fused op's packed parameter vector and per-gate matrices so fused and
unfused graphs interchange checkpoints, exactly like the reference.

The gluon cells (``gluon/rnn/rnn_cell.py``) are the modern path; this
package exists so reference code using ``mx.rnn.*`` runs unchanged.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from .. import symbol
from .. import initializer as init

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "ConvRNNCell", "ConvLSTMCell",
           "ConvGRUCell"]


class RNNParams:
    """Container for holding variables (parity: rnn_cell.py RNNParams).
    Cells sharing one RNNParams share weights."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.var(name, **kwargs)
        return self._params[name]


def _cells_state_info(cells):
    return sum([c.state_info for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Split/merge between a single (T-major or N-major) symbol and a
    per-step list (parity: rnn_cell.py _normalize_sequence)."""
    assert inputs is not None
    axis = layout.find("T")
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, symbol.Symbol):
        if merge is False:
            outs = symbol.SliceChannel(inputs, axis=in_axis,
                                        num_outputs=length,
                                        squeeze_axis=1)
            inputs = list(outs)
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.concat(*inputs, dim=axis)
            in_axis = axis
    if isinstance(inputs, symbol.Symbol) and axis != in_axis:
        inputs = symbol.swapaxes(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis




def _infer_batch(inputs, layout):
    """Batch size from an input symbol/list when statically known."""
    try:
        if isinstance(inputs, symbol.Symbol):
            return inputs.shape[layout.find("N")]
        return inputs[0].shape[0]
    except Exception:
        return 0


class BaseRNNCell:
    """Abstract base (parity: rnn_cell.py BaseRNNCell)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        if hasattr(self, "_cells"):
            for cell in self._cells:
                cell.reset()

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, batch_size=0, **kwargs):
        """Initial states.  ``batch_size`` (extension over the reference)
        substitutes unknown (0) dims so constants stay static-shaped on
        XLA; ``unroll`` fills it from the input symbol automatically."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix,
                                         self._init_counter)
            kw = dict(kwargs)
            if info is not None:
                kw.update(info)
                kw.pop("__layout__", None)
            shape = kw.get("shape")
            if shape is not None and batch_size:
                shape = tuple(batch_size if d == 0 else d for d in shape)
                kw["shape"] = shape
            if func in (symbol.zeros, symbol.ones) and shape is not None \
                    and any(d == 0 for d in shape):
                # unknown dims (batch) cannot materialize a constant on
                # XLA's static shapes; the state becomes a bindable
                # variable instead — simple_bind/Module feed zeros, which
                # reproduces the reference's deferred-shape zeros
                kw.pop("dtype", None)
                state = symbol.var(name, **kw)
            else:
                state = func(name=name, **kw)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Split fused per-cell i2h/h2h matrices into per-gate entries
        (parity: rnn_cell.py:225)."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ("i2h", "h2h"):
            for t in ("weight", "bias"):
                name = "%s%s_%s" % (self._prefix, group_name, t)
                if name not in args:
                    continue
                arr = args.pop(name)
                for j, gate in enumerate(self._gate_names):
                    wname = "%s%s%s_%s" % (self._prefix, group_name,
                                           gate, t)
                    args[wname] = arr[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """Inverse of ``unpack_weights`` (parity: rnn_cell.py:265)."""
        args = args.copy()
        if not self._gate_names:
            return args
        for group_name in ("i2h", "h2h"):
            for t in ("weight", "bias"):
                pieces = []
                for gate in self._gate_names:
                    wname = "%s%s%s_%s" % (self._prefix, group_name,
                                           gate, t)
                    if wname not in args:
                        pieces = None
                        break
                    pieces.append(args.pop(wname))
                if pieces:
                    name = "%s%s_%s" % (self._prefix, group_name, t)
                    args[name] = nd.concatenate(pieces)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell across ``length`` steps
        (parity: rnn_cell.py:295)."""
        self.reset()
        batch = _infer_batch(inputs, layout)
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Elman RNN cell (parity: rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(states[0], self._hW, self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (parity: rnn_cell.py LSTMCell); gate order i, f, c, o
    matches the fused RNN op."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias",
            init=init.LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(states[0], self._hW, self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = i2h + h2h
        sliced = list(symbol.SliceChannel(gates, num_outputs=4,
                                           name="%sslice" % name))
        in_gate = symbol.Activation(sliced[0], act_type="sigmoid")
        forget_gate = symbol.Activation(sliced[1], act_type="sigmoid")
        in_trans = symbol.Activation(sliced[2], act_type="tanh")
        out_gate = symbol.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (parity: rnn_cell.py GRUCell); gate order r, z, n."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(inputs, self._iW, self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(prev_h, self._hW, self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%sh2h" % name)
        i2h_r, i2h_z, i2h_n = list(symbol.SliceChannel(i2h, num_outputs=3))
        h2h_r, h2h_z, h2h_n = list(symbol.SliceChannel(h2h, num_outputs=3))
        reset = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h_n + reset * h2h_n,
                                       act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused RNN via the monolithic ``RNN`` op (parity:
    rnn_cell.py FusedRNNCell; cuDNN becomes a lax.scan chain)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._directions = ["l", "r"] if bidirectional else ["l"]
        initializer = init.FusedRNN(None, num_hidden, num_layers, mode,
                                    bidirectional, forget_bias)
        self._parameter = self.params.get("parameters", init=initializer)

    @property
    def state_info(self):
        b = self._bidirectional + 1
        n = (self._mode == "lstm") + 1
        return [{"shape": (b * self._num_layers, 0, self._num_hidden),
                 "__layout__": "LNC"} for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def __call__(self, inputs, states):
        raise MXNetError(
            "FusedRNNCell cannot be stepped — use unroll (the whole "
            "sequence is one fused op)")

    def _layer_param_shapes(self, num_input):
        """[(name, shape)] in the PACKED vector's order (weights of every
        layer/direction first, then biases — rnn-inl.h layout)."""
        h = self._num_hidden
        m = self._num_gates
        dirs = self._directions
        shapes = []
        for group in ("weight", "bias"):
            for layer in range(self._num_layers):
                in_size = num_input if layer == 0 \
                    else h * len(dirs)
                for d in dirs:
                    for conn in ("i2h", "h2h"):
                        for gate in self._gate_names:
                            name = "%s%s%d_%s%s_%s" % (
                                self._prefix, d, layer, conn, gate, group)
                            if group == "weight":
                                size = in_size if conn == "i2h" else h
                                shapes.append((name, (h, size)))
                            else:
                                shapes.append((name, (h,)))
        return shapes

    def unpack_weights(self, args):
        args = args.copy()
        if self._parameter.name not in args:
            return args  # already unpacked
        arr = args.pop(self._parameter.name)
        arr_np = arr.asnumpy().reshape(-1)
        b = len(self._directions)
        m = self._num_gates
        h = self._num_hidden
        num_input = arr_np.size // b // h // m \
            - (self._num_layers - 1) * (h + b * h + 2) - h - 2
        offset = 0
        for name, shape in self._layer_param_shapes(num_input):
            size = int(_np.prod(shape))
            args[name] = nd.array(
                arr_np[offset:offset + size].reshape(shape))
            offset += size
        assert offset == arr_np.size, "packed parameter size mismatch"
        return args

    def pack_weights(self, args):
        args = args.copy()
        w0_name = "%sl0_i2h%s_weight" % (self._prefix,
                                         self._gate_names[0])
        if w0_name not in args:
            return args  # already packed
        w0 = args[w0_name]
        num_input = w0.shape[1]
        pieces = []
        for name, shape in self._layer_param_shapes(num_input):
            # one-time parameter packing  # mxlint: allow-host-sync
            pieces.append(args.pop(name).asnumpy().reshape(-1))
        args[self._parameter.name] = nd.array(_np.concatenate(pieces))
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        batch = _infer_batch(inputs, layout)
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:  # RNN op wants TNC
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = self.begin_state(
                func=symbol.zeros, batch_size=batch, dtype="float32")
        states = begin_state
        if self._mode == "lstm":
            rnn = symbol.RNN(inputs, self._parameter, states[0],
                             states[1], state_size=self._num_hidden,
                             num_layers=self._num_layers,
                             bidirectional=self._bidirectional,
                             p=self._dropout, state_outputs=True,
                             mode=self._mode,
                             name="%srnn" % self._prefix)
        else:
            rnn = symbol.RNN(inputs, self._parameter, states[0],
                             state_size=self._num_hidden,
                             num_layers=self._num_layers,
                             bidirectional=self._bidirectional,
                             p=self._dropout, state_outputs=True,
                             mode=self._mode,
                             name="%srnn" % self._prefix)
        outputs = rnn[0]
        states = [rnn[1], rnn[2]] if self._mode == "lstm" else [rnn[1]]
        if axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs, _ = _normalize_sequence(length, outputs, layout,
                                             False, in_layout=layout)
        if not self._get_next_state:
            states = []
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (parity:
        rnn_cell.py:733)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix="%s_dropout%d_"
                    % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack cells sequentially (parity: rnn_cell.py:748)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                "Either specify params for SequentialRNNCell or child cells, not both."
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            begin_state = self.begin_state(
                batch_size=_infer_batch(inputs, layout))
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1
                else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout on cell input (parity: rnn_cell.py:827)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells that wrap another cell (parity: rnn_cell.py:867)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (parity: rnn_cell.py:909)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout; unfuse() first"
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout; wrap the cells"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell,
                                     self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) if p_outputs != 0. \
            else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the output (parity: rnn_cell.py:957)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout,
                                        merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [out + inp for out, inp in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence (parity:
    rnn_cell.py:998).  Step-by-step calling is impossible (the backward
    direction needs the whole sequence); use ``unroll``."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise MXNetError(
            "Bidirectional cannot be stepped. Please use unroll")

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        batch = _infer_batch(inputs, layout)
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch)
        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info)],
            layout=layout, merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):],
            layout=layout, merge_outputs=merge_outputs)
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, symbol.Symbol) \
                and isinstance(r_outputs, symbol.Symbol)
            l_outputs, _ = _normalize_sequence(length, l_outputs, layout,
                                               merge_outputs)
            r_outputs, _ = _normalize_sequence(length, r_outputs, layout,
                                               merge_outputs)
        if merge_outputs:
            r_outputs = symbol.reverse(r_outputs, axis=axis)
            outputs = symbol.concat(l_outputs, r_outputs, dim=2,
                                    name="%sout" % self._output_prefix)
        else:
            outputs = [
                symbol.concat(l_o, r_o, dim=1,
                              name="%st%d" % (self._output_prefix, i))
                for i, (l_o, r_o) in enumerate(
                    zip(l_outputs, reversed(r_outputs)))]
        states = l_states + r_states
        return outputs, states


class BaseConvRNNCell(BaseRNNCell):
    """Base for convolutional RNN cells (parity: rnn_cell.py:1094)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel, h2h_dilate,
                 i2h_kernel, i2h_stride, i2h_pad, i2h_dilate, activation,
                 prefix="", params=None, conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        self._h2h_kernel = h2h_kernel
        self._h2h_pad = (h2h_dilate[0] * (h2h_kernel[0] - 1) // 2,
                         h2h_dilate[1] * (h2h_kernel[1] - 1) // 2)
        self._h2h_dilate = h2h_dilate
        self._i2h_kernel = i2h_kernel
        self._i2h_stride = i2h_stride
        self._i2h_pad = i2h_pad
        self._i2h_dilate = i2h_dilate
        self._num_hidden = num_hidden
        self._input_shape = input_shape
        self._conv_layout = conv_layout
        self._activation = activation
        # infer state shape from a conv of the input shape
        data = symbol.var("__tmp__", shape=(1,) + tuple(input_shape))
        state = symbol.Convolution(
            data, symbol.var("__tmp_w__"), symbol.var("__tmp_b__"),
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate,
            num_filter=self._num_hidden, layout=conv_layout)
        self._state_shape = state.infer_shape(
            __tmp__=(1,) + tuple(input_shape))[1][0]
        self._state_shape = (0,) + self._state_shape[1:]
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hB = self.params.get("h2h_bias")

    @property
    def _num_gates(self):
        return len(self._gate_names)

    @property
    def state_info(self):
        return [{"shape": self._state_shape, "__layout__":
                 self._conv_layout}
                for _ in range(2 if isinstance(self, ConvLSTMCell) else 1)]

    def _conv_forward(self, inputs, states, name):
        i2h = symbol.Convolution(
            inputs, self._iW, self._iB, kernel=self._i2h_kernel,
            stride=self._i2h_stride, pad=self._i2h_pad,
            dilate=self._i2h_dilate,
            num_filter=self._num_hidden * self._num_gates,
            layout=self._conv_layout, name="%si2h" % name)
        h2h = symbol.Convolution(
            states[0], self._hW, self._hB, kernel=self._h2h_kernel,
            dilate=self._h2h_dilate, pad=self._h2h_pad,
            num_filter=self._num_hidden * self._num_gates,
            layout=self._conv_layout, name="%sh2h" % name)
        return i2h, h2h


class ConvRNNCell(BaseConvRNNCell):
    """Convolutional Elman cell (parity: rnn_cell.py:1176)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1), activation="tanh",
                 prefix="ConvRNN_", params=None, conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class ConvLSTMCell(BaseConvRNNCell):
    """Convolutional LSTM (parity: rnn_cell.py:1253; Shi et al. 2015)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1), activation="tanh",
                 prefix="ConvLSTM_", params=None, forget_bias=1.0,
                 conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        gates = i2h + h2h
        axis = 1 if self._conv_layout.startswith("NC") else 3
        sliced = list(symbol.SliceChannel(gates, num_outputs=4,
                                           axis=axis))
        in_gate = symbol.Activation(sliced[0], act_type="sigmoid")
        forget_gate = symbol.Activation(sliced[1], act_type="sigmoid")
        in_trans = self._get_activation(sliced[2], self._activation)
        out_gate = symbol.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * self._get_activation(next_c, self._activation)
        return next_h, [next_h, next_c]


class ConvGRUCell(BaseConvRNNCell):
    """Convolutional GRU (parity: rnn_cell.py:1349)."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1), activation="tanh",
                 prefix="ConvGRU_", params=None, conv_layout="NCHW"):
        super().__init__(input_shape, num_hidden, h2h_kernel, h2h_dilate,
                         i2h_kernel, i2h_stride, i2h_pad, i2h_dilate,
                         activation, prefix, params, conv_layout)

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        axis = 1 if self._conv_layout.startswith("NC") else 3
        i2h_r, i2h_z, i2h_n = list(symbol.SliceChannel(
            i2h, num_outputs=3, axis=axis))
        h2h_r, h2h_z, h2h_n = list(symbol.SliceChannel(
            h2h, num_outputs=3, axis=axis))
        reset = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = self._get_activation(i2h_n + reset * h2h_n,
                                          self._activation)
        next_h = (1.0 - update) * next_h_tmp + update * states[0]
        return next_h, [next_h]
