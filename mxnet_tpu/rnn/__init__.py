"""Legacy recurrent API (parity: ``python/mxnet/rnn/``)."""
from . import rnn_cell  # noqa: F401
from . import io  # noqa: F401
from . import rnn  # noqa: F401
from .rnn_cell import (  # noqa: F401
    RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell, FusedRNNCell,
    SequentialRNNCell, DropoutCell, ModifierCell, ZoneoutCell,
    ResidualCell, BidirectionalCell, ConvRNNCell, ConvLSTMCell,
    ConvGRUCell,
)
from .io import BucketSentenceIter, encode_sentences  # noqa: F401
from .rnn import (  # noqa: F401
    save_rnn_checkpoint, load_rnn_checkpoint, do_rnn_checkpoint,
)
