"""Bucketing text iterators (parity: python/mxnet/rnn/io.py)."""
from __future__ import annotations

import bisect
import random

import numpy as np

from .. import ndarray as nd
from ..io.io import DataIter, DataBatch, DataDesc


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Encode token lists as integer id lists (parity: io.py:30).

    Builds/extends ``vocab`` in place; returns (encoded, vocab).
    """
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                assert new_vocab or unknown_token is not None, \
                    "Unknown token %s" % word
                if unknown_token:
                    word = unknown_token
                else:
                    if idx == invalid_label:
                        idx += 1
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketing iterator for language models: label at each step is the
    next token (parity: io.py:84 BucketSentenceIter)."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32", layout="NT"):
        super().__init__()
        if not buckets:
            buckets = [i for i, j in enumerate(
                np.bincount([len(s) for s in sentences]))
                if j >= batch_size]
        buckets.sort()

        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = bisect.bisect_left(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        keep = [i for i, d in enumerate(self.data) if d]
        self.buckets = [buckets[i] for i in keep]
        self.data = [np.asarray(self.data[i], dtype=dtype) for i in keep]
        if ndiscard:
            print("WARNING: discarded %d sentences longer than the "
                  "largest bucket." % ndiscard)

        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.nddata = []
        self.ndlabel = []
        self.major_axis = layout.find("N")
        self.layout = layout
        self.default_bucket_key = max(self.buckets)

        if self.major_axis == 0:
            self.provide_data = [DataDesc(
                data_name, (batch_size, self.default_bucket_key))]
            self.provide_label = [DataDesc(
                label_name, (batch_size, self.default_bucket_key))]
        elif self.major_axis == 1:
            self.provide_data = [DataDesc(
                data_name, (self.default_bucket_key, batch_size))]
            self.provide_label = [DataDesc(
                label_name, (self.default_bucket_key, batch_size))]
        else:
            raise ValueError(
                "Invalid layout %s: Must by NT (batch major) or TN "
                "(time major)" % layout)

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1,
                                   batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(nd.array(buck.astype(self.dtype)))
            self.ndlabel.append(nd.array(label.astype(self.dtype)))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]
        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, data.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)])
