"""Deployment: compile a trained model to a portable StableHLO artifact.

Parity role: the reference's C predict API
(``src/c_api/c_predict_api.cc``, ``include/mxnet/c_predict_api.h`` — a
deployment-only ABI that loads ``model-symbol.json`` + ``.params`` and
runs inference without the Python frontend) and the ``amalgamation/``
single-file build of the same.

TPU-native mechanism: instead of replaying a symbol graph through an
interpreter, the whole trained forward is staged to **StableHLO** via
``jax.export`` and serialized.  The artifact is:

- self-contained — weights are baked in as constants (or kept as
  arguments with ``embed_params=False`` for A/B-able weights),
- ahead-of-time shape/dtype checked (calling with the wrong signature
  fails at load, like the predict API's provided-shape checks),
- loadable by ANY PJRT runtime that understands StableHLO — a C++
  server links PJRT and runs the module without this package (the C++
  story the reference's predict ABI served), and ``Predictor`` here is
  the in-process loader.

Versioning: jax.export guarantees forward/backward compatibility windows
for serialized modules, which replaces the reference's ``.params`` magic
-number versioning for deployment artifacts.
"""
from __future__ import annotations

import json
import os

import numpy as np

from .base import MXNetError


_MAGIC = b"MXTPU1\n"
_AOT_MAGIC = b"MXAOT1\n"  # compile_cache bundles (serving tier)


def export_serving_bundle(net, path, **kwargs):
    """Export a Llama-family ``net`` as an AOT serving bundle: the
    paged prefill/decode executable pair plus the KV-page geometry in
    the bundle meta.  Thin re-export of
    :func:`mxnet_tpu.serve.export_serving_bundle` so deployment code
    has one module to import for both artifact kinds.  See
    docs/serving.md."""
    from .serve.model import export_serving_bundle as _export

    return _export(net, path, **kwargs)


def load_serving_bundle(path, expect_geometry=None):
    """Load + validate a serving bundle: ``(KVGeometry, executables)``.

    All checks run at load time — bundle kind, complete KV-page
    geometry (page size, num pages, dtype, …), presence of every
    executable the geometry names, and agreement with
    ``expect_geometry`` when given — so a mismatched bundle fails here
    with a field-by-field error instead of inside XLA on the first
    decode."""
    from .serve.model import load_serving_executables

    return load_serving_executables(path, expect=expect_geometry)


def export_model(net, example_inputs, path, embed_params=True,
                 platforms=None):
    """Compile ``net``'s forward on ``example_inputs`` and write a
    deployable artifact to ``path`` (conventionally ``*.mxtpu``).

    ``example_inputs``: NDArray/ndarray tuple fixing input shapes+dtypes.
    ``embed_params=True`` bakes the weights into the module as
    constants; ``False`` keeps them as trailing arguments and stores
    them beside the module (loadable/updatable separately).
    ``platforms``: e.g. ``("tpu", "cpu")`` for a multi-platform module;
    defaults to the current backend.
    """
    import jax
    from jax import export as jexport

    from . import autograd
    from . import random as _random
    from .gluon import block as block_mod
    from .ndarray.ndarray import NDArray

    if not isinstance(example_inputs, (tuple, list)):
        example_inputs = (example_inputs,)
    xs = tuple(np.asarray(x.asnumpy() if isinstance(x, NDArray) else x)
               for x in example_inputs)
    # resolve deferred shapes with one forward — only when needed
    params = list(net.collect_params().values())
    if any(p._data is None for p in params):
        net(*[NDArray(np.asarray(x)) for x in xs])
        params = list(net.collect_params().values())
    weights = tuple(p.data().data() for p in params)

    def fwd(inputs, ws):
        st = block_mod._trace_st()
        prev = (st.param_map, st.aux_updates, st.active)
        st.param_map = {id(p): NDArray(w) for p, w in zip(params, ws)}
        st.aux_updates = []
        st.active = True
        try:
            with autograd.predict_mode(), \
                    _random.trace_key_scope(jax.random.PRNGKey(0)):
                out = net._forward_imperative(
                    *[NDArray(x) for x in inputs])
            if isinstance(out, (list, tuple)):
                return tuple(o.data() for o in out)
            return (out.data(),)
        finally:
            st.param_map, st.aux_updates, st.active = prev

    kwargs = {}
    if platforms is not None:
        kwargs["platforms"] = tuple(platforms)

    if embed_params:
        fn = jax.jit(lambda *inputs: fwd(inputs, weights))
        exp = jexport.export(fn, **kwargs)(*xs)
        blobs = {}
    else:
        fn = jax.jit(lambda inputs, ws: fwd(inputs, ws))
        exp = jexport.export(fn, **kwargs)(xs, weights)
        blobs = {"param_%05d" % i: np.asarray(w)
                 for i, w in enumerate(weights)}

    module = exp.serialize()
    meta = {
        "embed_params": bool(embed_params),
        "n_inputs": len(xs),
        "n_params": len(params),
        "param_names": [p.name for p in params],
        "param_shapes": [list(np.asarray(w).shape) for w in weights],
        "param_dtypes": [str(np.asarray(w).dtype) for w in weights],
        "input_shapes": [list(x.shape) for x in xs],
        "input_dtypes": [str(x.dtype) for x in xs],
        "platforms": list(exp.platforms),
    }
    with open(path, "wb") as f:
        f.write(_MAGIC)
        head = json.dumps(meta).encode()
        f.write(len(head).to_bytes(8, "little"))
        f.write(head)
        f.write(len(module).to_bytes(8, "little"))
        f.write(module)
        if blobs:
            import io as _io

            buf = _io.BytesIO()
            np.savez(buf, **blobs)
            f.write(buf.getvalue())
    return meta


class Predictor:
    """In-process loader for exported artifacts (parity:
    ``MXPredCreate``/``MXPredForward``/``MXPredGetOutput``)."""

    def __init__(self, path):
        with open(path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic == _AOT_MAGIC:
                # an AOT serving bundle, not a StableHLO artifact: say so
                # (and validate its KV geometry) instead of failing as a
                # generic bad-magic or, worse, later inside XLA
                from .serve.model import read_bundle_geometry

                geometry, _ = read_bundle_geometry(path)
                raise MXNetError(
                    "%s is an AOT serving bundle [%s], not a StableHLO "
                    "artifact — load it with serve.LlamaServer(path) or "
                    "deploy.load_serving_bundle(path)"
                    % (path, geometry.describe()))
            if magic != _MAGIC:
                raise MXNetError("%s is not an exported model" % path)
            hlen = int.from_bytes(f.read(8), "little")
            self.meta = json.loads(f.read(hlen).decode())
            mlen = int.from_bytes(f.read(8), "little")
            module = f.read(mlen)
            rest = f.read()
        from jax import export as jexport

        self._exp = jexport.deserialize(module)
        self._weights = ()
        if not self.meta["embed_params"] and self.meta["n_params"]:
            import io as _io

            # validate the weight blobs AT LOAD (parity: the predict API's
            # provided-shape checks) — a truncated artifact or one whose
            # stored weights no longer match the module signature must fail
            # here, not as an opaque XLA error on the first request
            try:
                blobs = np.load(_io.BytesIO(rest))
                ws = tuple(blobs["param_%05d" % i]
                           for i in range(self.meta["n_params"]))
            except MXNetError:
                raise
            except Exception as e:
                raise MXNetError(
                    "%s: embed_params=False artifact is missing/corrupt "
                    "weight blobs (%s: %s)" % (path, type(e).__name__, e))
            self._check_param_sig(ws, path)
            self._weights = ws

    def _check_param_sig(self, arrays, origin="set_params"):
        shapes = self.meta.get("param_shapes")
        dtypes = self.meta.get("param_dtypes")
        if shapes is None:
            return  # pre-param-sig artifact: best effort
        for i, (a, shape, dt) in enumerate(zip(arrays, shapes, dtypes)):
            if list(a.shape) != shape or str(a.dtype) != dt:
                raise MXNetError(
                    "%s: param %d (%s) mismatch: got %s %s, module wants "
                    "%s %s" % (origin, i,
                               self.meta["param_names"][i],
                               tuple(a.shape), a.dtype, tuple(shape), dt))

    def set_params(self, arrays):
        """Swap the weights of a ``embed_params=False`` artifact.

        Shape/dtype-checked against the module signature immediately — a
        wrong weight set raises HERE, not on the next ``predict``.
        """
        if self.meta["embed_params"]:
            raise MXNetError("artifact has embedded params")
        if len(arrays) != self.meta["n_params"]:
            raise MXNetError("expected %d params" % self.meta["n_params"])
        ws = tuple(np.asarray(a) for a in arrays)
        self._check_param_sig(ws)
        self._weights = ws

    def warm(self):
        """Pre-compile the module before the first request.

        Runs the exported forward once on zeros shaped from the artifact's
        input signature, so the PJRT compile (disk-cached via
        compile_cache.py when MXNET_COMPILE_CACHE is on) happens at server
        startup instead of on the first live request.  Returns ``self``
        for ``Predictor(path).warm()`` chaining.
        """
        zeros = tuple(
            np.zeros(shape, dtype=dt)
            for shape, dt in zip(self.meta["input_shapes"],
                                 self.meta["input_dtypes"]))
        if self.meta["embed_params"]:
            self._exp.call(*zeros)
        else:
            if len(self._weights) != self.meta["n_params"]:
                raise MXNetError(
                    "warm() before set_params on an embed_params=False "
                    "artifact with no stored weights")
            self._exp.call(zeros, self._weights)
        return self

    def predict(self, *inputs):
        """Run the compiled forward; returns NDArray or list of them."""
        from .ndarray.ndarray import NDArray

        xs = tuple(np.asarray(x.asnumpy() if isinstance(x, NDArray) else x)
                   for x in inputs)
        if len(xs) != self.meta["n_inputs"]:
            raise MXNetError("expected %d inputs" % self.meta["n_inputs"])
        for x, shape, dt in zip(xs, self.meta["input_shapes"],
                                self.meta["input_dtypes"]):
            if list(x.shape) != shape or str(x.dtype) != dt:
                raise MXNetError(
                    "input mismatch: got %s %s, artifact wants %s %s"
                    % (x.shape, x.dtype, tuple(shape), dt))
        if self.meta["embed_params"]:
            outs = self._exp.call(*xs)
        else:
            outs = self._exp.call(xs, self._weights)
        if isinstance(outs, (list, tuple)):
            res = [NDArray(o) for o in outs]
            return res[0] if len(res) == 1 else res
        return NDArray(outs)

    @property
    def mlir(self):
        """StableHLO text of the deployed module (debugging/audit)."""
        return self._exp.mlir_module()
