"""``mx.contrib`` — contrib namespaces (parity: python/mxnet/contrib/)."""
from .. import amp  # noqa: F401
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from . import tensorboard  # noqa: F401
from . import svrg_optimization  # noqa: F401
