"""``mx.contrib`` — contrib namespaces (parity: python/mxnet/contrib/)."""
from .. import amp  # noqa: F401  (reference path mx.contrib.amp)
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from . import tensorboard  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import io  # noqa: F401
from . import autograd  # noqa: F401
from .io import DataLoaderIter  # noqa: F401
from .autograd import TrainingStateScope  # noqa: F401
