"""``mx.contrib`` — contrib namespaces (parity: python/mxnet/contrib/)."""
from .. import amp  # noqa: F401
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
