"""TensorBoard metric logging (parity: contrib/tensorboard.py).

The reference bridges to ``mxboard``; this image ships ``tensorboardX``,
which exposes the same ``SummaryWriter.add_scalar`` API — the callback
degrades to a logged error when neither is importable, exactly like the
reference's mxboard-missing path.
"""
from __future__ import annotations

import logging


class LogMetricsCallback:
    """Log eval-metric values per epoch to a TensorBoard event file
    (parity: contrib/tensorboard.py:25 LogMetricsCallback).

    Use as ``batch_end_callback``/``eval_end_callback`` with
    ``Module.fit`` or as an Estimator event handler — any callable fed
    a param object carrying ``eval_metric`` works.
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = None
        try:
            try:
                from mxboard import SummaryWriter
            except ImportError:
                from tensorboardX import SummaryWriter
            self.summary_writer = SummaryWriter(logging_dir)
        except ImportError:
            logging.error(
                "You can install mxboard via `pip install mxboard` or "
                "tensorboardX via `pip install tensorboardX`.")

    def __call__(self, param):
        """Write each (name, value) of ``param.eval_metric``."""
        if self.summary_writer is None:
            return
        if getattr(param, "eval_metric", None) is None:
            return
        step = getattr(param, "epoch", 0)
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, global_step=step)
