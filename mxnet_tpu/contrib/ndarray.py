"""Parity import path: ``mx.contrib.ndarray`` — the contrib op namespace
(reference ``python/mxnet/contrib/ndarray.py`` codegen).  The live registry
already exposes every ``_contrib_*`` op as ``mx.nd.contrib.<name>``; this
module forwards attribute access to that namespace object."""


def __getattr__(name):
    from .. import ndarray as _nd

    return getattr(_nd.contrib, name)


def __dir__():
    from .. import ndarray as _nd

    return dir(_nd.contrib)
