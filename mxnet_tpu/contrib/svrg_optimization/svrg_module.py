"""SVRG training module (parity: contrib/svrg_optimization/svrg_module.py).

SVRG (Johnson & Zhang 2013) reduces gradient variance: every
``update_freq`` epochs the module snapshots the weights w̃ and computes
the FULL-dataset gradient ḡ at w̃; each minibatch step then uses the
corrected gradient  g_i(w) − g_i(w̃) + ḡ.

The reference wires this through a wrapper optimizer and special KVStore
keys (``svrg_optimizer.py`` ``_SVRGOptimizer``/``_AssignmentOptimizer``).
TPU-native mechanism: a second internal Module holds the snapshot
weights, both modules' forward/backward are fused jitted executables,
and the correction is applied directly to the gradient buffers before
the optimizer step — no KVStore round-trip, identical math.
"""
from __future__ import annotations

import logging

import numpy as np

from ...base import MXNetError
from ...module.module import Module
from ... import ndarray as nd


class SVRGModule(Module):
    """Module with Stochastic Variance Reduced Gradient updates
    (parity: svrg_module.py:30 SVRGModule).

    Parameters beyond ``Module``: ``update_freq`` — number of epochs
    between full-gradient snapshots.
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=None,
                 context=None, update_freq=1, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, **kwargs)
        if not isinstance(update_freq, int) or update_freq < 1:
            raise MXNetError("update_freq must be a positive integer")
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, **kwargs)
        self._param_dict = None
        self._ctx_len = 1

    # -- lifecycle --------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module,
                     grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind, None,
                               grad_req)

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        super().init_params(initializer, arg_params, aux_params,
                            allow_missing, force_init, allow_extra)
        self._sync_aux_params()
        # full-gradient accumulators, one per parameter
        self._param_dict = {
            name: nd.zeros(self._exec_group._exec.arg_dict[name].shape)
            for name in self._exec_group.param_names}

    def _sync_aux_params(self):
        """Copy current weights into the snapshot module (w̃ ← w)."""
        arg, aux = self.get_params()
        self._mod_aux.init_params(arg_params=arg, aux_params=aux,
                                  allow_missing=False, force_init=True)

    # -- SVRG mechanics ---------------------------------------------------
    def update_full_grads(self, train_data):
        """Compute the full-dataset gradient at the snapshot weights
        (parity: svrg_module.py:292)."""
        self._sync_aux_params()
        train_data.reset()
        nbatch = 0
        totals = {n: None for n in self._exec_group.param_names}
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            gdict = self._mod_aux._exec_group._exec.grad_dict
            for name in totals:
                g = gdict.get(name)
                if g is None:
                    continue
                acc = totals[name]
                totals[name] = g.copy() if acc is None else acc + g
            nbatch += 1
        if nbatch == 0:
            raise MXNetError("update_full_grads: empty data iterator")
        for name, acc in totals.items():
            if acc is not None:
                self._param_dict[name] = acc / nbatch
        train_data.reset()

    def forward_backward(self, data_batch):
        """Forward+backward with the SVRG gradient correction applied in
        place (parity: svrg_module.py fit_ inner loop)."""
        self.forward(data_batch, is_train=True)
        self.backward()
        self._mod_aux.forward(data_batch, is_train=True)
        self._mod_aux.backward()
        exec_ = self._exec_group._exec
        aux_exec = self._mod_aux._exec_group._exec
        for name in self._exec_group.param_names:
            g = exec_.grad_dict.get(name)
            if g is None:
                continue
            g_tilde = aux_exec.grad_dict.get(name)
            corrected = g - g_tilde + self._param_dict[name]
            g._set_data(corrected.data())

    # -- reference-style fit ---------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=None,
            validation_metric=None, force_init=False):
        """Train with periodic full-gradient snapshots (parity:
        svrg_module.py:400 fit)."""
        from ... import metric as metric_mod
        from ... import initializer as init_mod

        assert num_epoch is not None, "please specify number of epochs"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True)
        self.init_params(initializer=initializer
                         or init_mod.Uniform(0.01),
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    from ...model import BatchEndParam

                    cbs = batch_end_callback \
                        if isinstance(batch_end_callback, (list, tuple)) \
                        else [batch_end_callback]
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in cbs:
                        cb(param)
            if epoch_end_callback is not None:
                arg, aux = self.get_params()
                cbs = epoch_end_callback \
                    if isinstance(epoch_end_callback, (list, tuple)) \
                    else [epoch_end_callback]
                for cb in cbs:
                    cb(epoch, self._symbol, arg, aux)
            logging.getLogger(__name__).info(
                "Epoch[%d] SVRG train %s", epoch,
                dict(eval_metric.get_name_value()))
