"""SVRG optimization (parity: ``python/mxnet/contrib/svrg_optimization``)."""
from .svrg_module import SVRGModule  # noqa: F401
