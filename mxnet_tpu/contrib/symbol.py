"""Parity import path: ``mx.contrib.symbol`` (reference
``python/mxnet/contrib/symbol.py`` codegen) — the symbolic contrib ops."""


def __getattr__(name):
    from .. import symbol as _sym

    return getattr(_sym.contrib, name)


def __dir__():
    from .. import symbol as _sym

    return dir(_sym.contrib)
