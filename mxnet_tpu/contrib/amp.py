"""Parity import path: the reference ships AMP as ``mx.contrib.amp``
(``python/mxnet/contrib/amp/amp.py``); this rebuild hosts it at
``mxnet_tpu.amp`` (bfloat16-first).  Re-export so reference recipes'
``from mxnet.contrib import amp`` works unchanged."""
from ..amp import *  # noqa: F401,F403
from ..amp import (  # noqa: F401
    init, init_trainer, scale_loss, convert_hybrid_block, LossScaler,
)
