"""Text token indexing + embeddings (parity:
``python/mxnet/contrib/text/``)."""
from . import utils  # noqa: F401
from . import vocab  # noqa: F401
from . import embedding  # noqa: F401
from .vocab import Vocabulary  # noqa: F401
