"""Pretrained token embeddings (parity: contrib/text/embedding.py).

Same composable API as the reference: a registry of embedding classes
(``register``/``create``/``get_pretrained_file_names``), a
``_TokenEmbedding`` base that extends ``Vocabulary`` with an
``idx_to_vec`` matrix, file-format loaders (one token + vector per line),
``CustomEmbedding`` for arbitrary local files, and ``CompositeEmbedding``
to stack several embeddings over one vocabulary.

This image has zero network egress, so ``GloVe``/``FastText`` resolve
their pretrained files from ``embedding_root`` ONLY (the reference's
download step becomes "file must already be on disk" — same cache
layout, no silent network I/O).
"""
from __future__ import annotations

import io
import logging
import os

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd
from .vocab import Vocabulary

_REGISTRY = {}


def register(embedding_cls):
    """Register an embedding class under its lowercase name
    (parity: embedding.py:43)."""
    _REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding (parity: embedding.py:66)."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise MXNetError(
            "Cannot find `embedding_name` %s. Use get_pretrained_file_names"
            "() to get all the valid embedding names." % embedding_name)
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names per embedding (parity:
    embedding.py:93)."""
    if embedding_name is not None:
        name = embedding_name.lower()
        if name not in _REGISTRY:
            raise MXNetError(
                "Cannot find `embedding_name` %s." % embedding_name)
        return list(_REGISTRY[name].pretrained_file_name_sha1)
    return {n: list(c.pretrained_file_name_sha1)
            for n, c in _REGISTRY.items()}


class _TokenEmbedding(Vocabulary):
    """Vocabulary + vector table (parity: embedding.py:136)."""

    pretrained_file_name_sha1 = {}

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @classmethod
    def _get_pretrained_file(cls, embedding_root, pretrained_file_name):
        embedding_root = os.path.expanduser(embedding_root)
        embedding_dir = os.path.join(embedding_root,
                                     cls.__name__.lower())
        path = os.path.join(embedding_dir, pretrained_file_name)
        if not os.path.isfile(path):
            raise MXNetError(
                "pretrained file %s not found under %s; this environment "
                "has no network access — place the file there first"
                % (pretrained_file_name, embedding_dir))
        return path

    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        """Parse one-token-per-line vectors (parity: embedding.py:235)."""
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise MXNetError(
                "`pretrained_file_path` must be a valid path to the "
                "pre-trained token embedding file.")
        vec_len = None
        all_elems = []
        tokens = set()
        loaded_unknown_vec = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                elems = line.rstrip().split(elem_delim)
                assert len(elems) > 1, (
                    "line %d in %s: unexpected data format."
                    % (line_num, pretrained_file_path))
                token, elems = elems[0], [float(e) for e in elems[1:]]
                if token == self.unknown_token and \
                        loaded_unknown_vec is None:
                    loaded_unknown_vec = elems
                elif token in tokens:
                    logging.warning(
                        "duplicate embedding found for token %r; only the "
                        "first occurrence is kept", token)
                elif len(elems) == 1:
                    # likely a header line (e.g. fastText "count dim");
                    # reference skips any 1-dim vector with a warning
                    logging.warning(
                        "line %d: token %r with 1-dimensional vector is "
                        "likely a header and is skipped", line_num, token)
                else:
                    if vec_len is None:
                        vec_len = len(elems)
                        # index 0 reserved for unknown_token
                        all_elems.extend([0.0] * vec_len)
                    else:
                        assert len(elems) == vec_len, (
                            "line %d in %s: inconsistent vector length"
                            % (line_num, pretrained_file_path))
                    all_elems.extend(elems)
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = \
                        len(self._idx_to_token) - 1
                    tokens.add(token)
        self._vec_len = vec_len or 0
        mat = np.asarray(all_elems, np.float32).reshape(
            (-1, self._vec_len)) if self._vec_len else \
            np.zeros((1, 0), np.float32)
        if loaded_unknown_vec is None:
            mat[0] = init_unknown_vec(shape=self._vec_len).asnumpy() \
                if hasattr(init_unknown_vec(shape=self._vec_len),
                           "asnumpy") \
                else np.asarray(init_unknown_vec(shape=self._vec_len))
        else:
            mat[0] = np.asarray(loaded_unknown_vec, np.float32)
        self._idx_to_vec = nd.array(mat)

    def _index_tokens_from_vocabulary(self, vocabulary):
        self._idx_to_token = vocabulary.idx_to_token[:]
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = None if vocabulary.reserved_tokens is None \
            else vocabulary.reserved_tokens[:]

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len,
                                      vocab_idx_to_token):
        """Compose vectors for a vocabulary from source embeddings
        (parity: embedding.py:320)."""
        new_vec_len = sum(e.vec_len for e in token_embeddings)
        rows = np.zeros((vocab_len, new_vec_len), np.float32)
        col_start = 0
        for emb in token_embeddings:
            col_end = col_start + emb.vec_len
            rows[:, col_start:col_end] = emb.get_vecs_by_tokens(
                list(vocab_idx_to_token)).asnumpy()
            col_start = col_end
        self._vec_len = new_vec_len
        self._idx_to_vec = nd.array(rows)

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknowns get row 0
        (parity: embedding.py:373)."""
        to_reduce = False
        if not isinstance(tokens, list):
            tokens = [tokens]
            to_reduce = True
        if not lower_case_backup:
            indices = [self.token_to_idx.get(t, 0) for t in tokens]
        else:
            indices = [self.token_to_idx[t] if t in self.token_to_idx
                       else self.token_to_idx.get(t.lower(), 0)
                       for t in tokens]
        mat = self._idx_to_vec.asnumpy()[np.asarray(indices, np.int64)]
        vecs = nd.array(mat)
        return vecs[0] if to_reduce else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of existing tokens (parity:
        embedding.py:418)."""
        assert self._idx_to_vec is not None, \
            "The property `idx_to_vec` has not been properly set."
        if not isinstance(tokens, list) or len(tokens) == 1:
            assert hasattr(new_vectors, "shape") and \
                len(new_vectors.shape) in (1, 2), \
                "`new_vectors` must be a 1-D or 2-D NDArray"
            if not isinstance(tokens, list):
                tokens = [tokens]
        vecs = new_vectors.asnumpy().reshape(len(tokens), -1)
        mat = self._idx_to_vec.asnumpy().copy()
        for t, v in zip(tokens, vecs):
            if t not in self.token_to_idx:
                raise MXNetError(
                    "token %r is unknown; only vectors of indexed tokens "
                    "can be updated" % t)
            mat[self.token_to_idx[t]] = v
        self._idx_to_vec = nd.array(mat)

    def _build_embedding_for_vocabulary(self, vocabulary):
        """Re-index this embedding onto ``vocabulary`` (shared by every
        concrete class; reference keeps it on _TokenEmbedding too)."""
        emb = CompositeEmbedding(vocabulary, [self])
        self._index_tokens_from_vocabulary(vocabulary)
        self._vec_len = emb.vec_len
        self._idx_to_vec = emb.idx_to_vec

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if pretrained_file_name not in cls.pretrained_file_name_sha1:
            raise MXNetError(
                "Cannot find pretrained file %s for %s. Valid names: %s"
                % (pretrained_file_name, cls.__name__,
                   ", ".join(cls.pretrained_file_name_sha1)))


@register
class GloVe(_TokenEmbedding):
    """GloVe embeddings from a local cache (parity: embedding.py:484)."""

    pretrained_file_name_sha1 = {
        n: "" for n in (
            "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
            "glove.6B.200d.txt", "glove.6B.300d.txt",
            "glove.840B.300d.txt", "glove.twitter.27B.25d.txt",
            "glove.twitter.27B.50d.txt", "glove.twitter.27B.100d.txt",
            "glove.twitter.27B.200d.txt")}

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=os.path.join("~", ".mxnet", "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = self._get_pretrained_file(embedding_root,
                                         pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(_TokenEmbedding):
    """fastText embeddings from a local cache (parity:
    embedding.py:556)."""

    pretrained_file_name_sha1 = {
        n: "" for n in ("wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec",
                        "wiki.fr.vec", "wiki.de.vec", "wiki.es.vec")}

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join("~", ".mxnet", "embeddings"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        self._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = self._get_pretrained_file(embedding_root,
                                         pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


class CustomEmbedding(_TokenEmbedding):
    """Embedding from any local token-vector file (parity:
    embedding.py:638)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


class CompositeEmbedding(_TokenEmbedding):
    """Stack several embeddings over one vocabulary (parity:
    embedding.py:680)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        for emb in token_embeddings:
            assert isinstance(emb, _TokenEmbedding), \
                "`token_embeddings` must be instances of _TokenEmbedding"
        self._index_tokens_from_vocabulary(vocabulary)
        self._set_idx_to_vec_by_embeddings(
            token_embeddings, len(self), self.idx_to_token)
