"""Token indexing (parity: python/mxnet/contrib/text/vocab.py Vocabulary).

Builds index maps from a frequency counter with the reference's exact
ordering contract: unknown token at index 0, reserved tokens next, then
counter keys by descending frequency (ties broken by insertion/__cmp__
order) subject to ``most_freq_count`` / ``min_freq``.
"""
from __future__ import annotations

from ...base import MXNetError


class Vocabulary:
    """Indexing for text tokens (parity: vocab.py:30)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("`min_freq` must be set to a positive value")
        if reserved_tokens is not None:
            reserved_set = set(reserved_tokens)
            if unknown_token in reserved_set:
                raise MXNetError(
                    "`reserved_tokens` must not contain unknown_token")
            if len(reserved_set) != len(reserved_tokens):
                raise MXNetError(
                    "`reserved_tokens` must not contain duplicates")
        self._index_unknown_and_reserved_tokens(unknown_token,
                                                reserved_tokens)
        if counter is not None:
            self._index_counter_keys(counter, unknown_token,
                                     reserved_tokens, most_freq_count,
                                     min_freq)

    def _index_unknown_and_reserved_tokens(self, unknown_token,
                                           reserved_tokens):
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        if reserved_tokens is None:
            self._reserved_tokens = None
        else:
            self._reserved_tokens = list(reserved_tokens)
            self._idx_to_token.extend(reserved_tokens)
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}

    def _index_counter_keys(self, counter, unknown_token, reserved_tokens,
                            most_freq_count, min_freq):
        unknown_and_reserved = {unknown_token}
        if reserved_tokens is not None:
            unknown_and_reserved.update(reserved_tokens)
        token_freqs = sorted(counter.items(), key=lambda x: x[0])
        token_freqs.sort(key=lambda x: x[1], reverse=True)
        token_cap = len(unknown_and_reserved) + (
            len(counter) if most_freq_count is None else most_freq_count)
        for token, freq in token_freqs:
            if freq < min_freq or len(self._idx_to_token) == token_cap:
                break
            if token in unknown_and_reserved:
                continue
            self._idx_to_token.append(token)
            self._token_to_idx[token] = len(self._idx_to_token) - 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) → index/indices; unknown tokens map to index 0
        (parity: vocab.py:162)."""
        to_reduce = False
        if not isinstance(tokens, list):
            tokens = [tokens]
            to_reduce = True
        indices = [self._token_to_idx.get(t, 0) for t in tokens]
        return indices[0] if to_reduce else indices

    def to_tokens(self, indices):
        """Index/indices → token(s) (parity: vocab.py:188)."""
        to_reduce = False
        if not isinstance(indices, list):
            indices = [indices]
            to_reduce = True
        max_idx = len(self._idx_to_token) - 1
        tokens = []
        for idx in indices:
            if not isinstance(idx, int) or idx > max_idx:
                raise MXNetError(
                    "Token index %r in the provided `indices` is invalid"
                    % idx)
            tokens.append(self._idx_to_token[idx])
        return tokens[0] if to_reduce else tokens
