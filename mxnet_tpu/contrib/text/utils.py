"""Text utilities (parity: python/mxnet/contrib/text/utils.py)."""
from __future__ import annotations

import collections
import re


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count tokens in a delimited string (parity: utils.py:28).

    Returns a ``collections.Counter`` mapping token -> frequency; pass
    ``counter_to_update`` to accumulate across documents.
    """
    source_str = re.split(token_delim + "|" + seq_delim, source_str)
    tokens = [t for t in source_str if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter
