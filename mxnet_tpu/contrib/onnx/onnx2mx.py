"""ONNX → MXNet Symbol import.

Reference parity: ``python/mxnet/contrib/onnx/onnx2mx/import_model.py`` +
``_import_helper.py`` op map.  Same public API —
``import_model(model_file) -> (sym, arg_params, aux_params)`` — decoding
with the in-repo protobuf codec.

BatchNormalization moving statistics import as auxiliary states (same
split the reference importer produces), so ``SymbolBlock``/``Module``
bind them the reference way.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import _proto as P

_ONNX2MX = {}


def onnx_op(*names):
    def deco(fn):
        for n in names:
            _ONNX2MX[n] = fn
        return fn
    return deco


_DT2NP = {P.DT_FLOAT: np.float32, P.DT_DOUBLE: np.float64,
          P.DT_FLOAT16: np.float16, P.DT_INT32: np.int32,
          P.DT_INT64: np.int64, P.DT_INT8: np.int8,
          P.DT_UINT8: np.uint8, P.DT_BOOL: np.bool_}
try:
    import ml_dtypes as _mld

    _DT2NP[P.DT_BFLOAT16] = _mld.bfloat16
except ImportError:  # bf16 models just fail with a clear dtype error
    pass


def _tensor_to_np(t):
    dt = _DT2NP.get(t.get("data_type", P.DT_FLOAT))
    if dt is None:
        raise MXNetError("unsupported tensor dtype %s" % t.get("data_type"))
    dims = tuple(t.get("dims", ()))
    if t.get("raw_data") is not None:
        if t.get("data_type") == P.DT_BFLOAT16:
            arr = np.frombuffer(t["raw_data"], np.uint16).view(dt)
        else:
            arr = np.frombuffer(t["raw_data"], dtype=dt)
    elif t.get("float_data"):
        arr = np.asarray(t["float_data"], np.float32).astype(dt)
    elif t.get("int64_data"):
        arr = np.asarray(t["int64_data"], np.int64).astype(dt)
    elif t.get("int32_data"):
        arr = np.asarray(t["int32_data"], np.int32).astype(dt)
    elif t.get("double_data"):
        arr = np.asarray(t["double_data"], np.float64).astype(dt)
    else:
        arr = np.zeros(dims, dt)
    return arr.reshape(dims)


def _attrs(node):
    out = {}
    for a in node.get("attribute", []):
        t = a.get("type")
        if t == P.ATTR_FLOAT:
            out[a["name"]] = a.get("f", 0.0)
        elif t == P.ATTR_INT:
            out[a["name"]] = a.get("i", 0)
        elif t == P.ATTR_STRING:
            v = a.get("s", b"")
            out[a["name"]] = v.decode() if isinstance(v, bytes) else v
        elif t == P.ATTR_INTS:
            out[a["name"]] = list(a.get("ints", []))
        elif t == P.ATTR_FLOATS:
            out[a["name"]] = list(a.get("floats", []))
        elif t == P.ATTR_TENSOR:
            out[a["name"]] = _tensor_to_np(a["t"])
    return out


class _Importer:
    def __init__(self):
        from ... import symbol as S

        self.S = S
        self.values = {}      # onnx value name -> Symbol
        self.consts = {}      # value name -> np.ndarray (initializers)
        self.params = {}      # var name -> np.ndarray actually referenced
        self.aux = set()

    def sym_of(self, name, as_param=True):
        if name in self.values:
            return self.values[name]
        if name in self.consts:
            arr = self.consts[name]
            v = self.S.var(name, shape=arr.shape, dtype=str(arr.dtype))
            self.values[name] = v
            self.params[name] = arr
            return v
        raise MXNetError("ONNX import: undefined value %r" % name)

    def const_of(self, name):
        """Numpy value of a constant input (shape tensors etc.)."""
        if name in self.consts:
            return self.consts[name]
        raise MXNetError(
            "ONNX import: %r must be a constant initializer" % name)


def _halve_pads(pads):
    n = len(pads) // 2
    begin, end = pads[:n], pads[n:]
    if list(begin) != list(end):
        raise MXNetError("asymmetric pads %s not supported" % (pads,))
    return [int(p) for p in begin]


@onnx_op("Conv")
def _conv(imp, node, a):
    ins = node["input"]
    data, w = imp.sym_of(ins[0]), imp.sym_of(ins[1])
    bias = imp.sym_of(ins[2]) if len(ins) > 2 else None
    wshape = imp.consts.get(ins[1])
    kernel = a.get("kernel_shape") or list(wshape.shape[2:])
    num_filter = int(wshape.shape[0]) if wshape is not None else 0
    kw = dict(kernel=tuple(int(k) for k in kernel),
              num_filter=num_filter,
              stride=tuple(int(s) for s in a.get("strides",
                                                 [1] * len(kernel))),
              dilate=tuple(int(d) for d in a.get("dilations",
                                                 [1] * len(kernel))),
              pad=tuple(_halve_pads(a.get("pads", [0] * 2 * len(kernel)))),
              num_group=int(a.get("group", 1)))
    if bias is None:
        return imp.S.Convolution(data, w, no_bias=True, **kw)
    return imp.S.Convolution(data, w, bias, no_bias=False, **kw)


@onnx_op("BatchNormalization")
def _bn(imp, node, a):
    ins = node["input"]
    data = imp.sym_of(ins[0])
    gamma, beta = imp.sym_of(ins[1]), imp.sym_of(ins[2])
    mean, var = imp.sym_of(ins[3]), imp.sym_of(ins[4])
    imp.aux.update([ins[3], ins[4]])
    out = imp.S.BatchNorm(data, gamma, beta, mean, var,
                          eps=float(a.get("epsilon", 1e-5)),
                          momentum=float(a.get("momentum", 0.9)),
                          fix_gamma=False)
    return out[0]


@onnx_op("Gemm")
def _gemm(imp, node, a):
    ins = node["input"]
    x, w = imp.sym_of(ins[0]), imp.sym_of(ins[1])
    alpha, beta = a.get("alpha", 1.0), a.get("beta", 1.0)
    if a.get("transA"):
        x = imp.S.transpose(x, axes=(1, 0))
    transB = bool(a.get("transB"))
    if abs(alpha - 1.0) > 1e-12:
        x = imp.S._mul_scalar(x, scalar=float(alpha))
    if not transB:
        w = imp.S.transpose(w, axes=(1, 0))
    bias = None
    if len(ins) > 2:
        bias = imp.sym_of(ins[2])
        if abs(beta - 1.0) > 1e-12:
            bias = imp.S._mul_scalar(bias, scalar=float(beta))
    wshape = imp.consts.get(ins[1])
    nh = 0
    if wshape is not None:
        nh = int(wshape.shape[0] if transB else wshape.shape[1])
    if bias is None:
        return imp.S.FullyConnected(x, w, no_bias=True, num_hidden=nh,
                                    flatten=False)
    return imp.S.FullyConnected(x, w, bias, no_bias=False, num_hidden=nh,
                                flatten=False)


@onnx_op("MatMul")
def _matmul(imp, node, a):
    x, y = imp.sym_of(node["input"][0]), imp.sym_of(node["input"][1])
    return imp.S.linalg_gemm2(x, y)


for _onn, _mxn in [("Relu", "relu"), ("Sigmoid", "sigmoid"),
                   ("Tanh", "tanh"), ("Erf", "erf"), ("Sqrt", "sqrt"),
                   ("Exp", "exp"), ("Log", "log"), ("Neg", "negative"),
                   ("Abs", "abs"), ("Floor", "floor"), ("Ceil", "ceil"),
                   ("Sin", "sin"), ("Cos", "cos"),
                   ("Identity", "_copy")]:
    def _mk(mxn):
        def f(imp, node, a):
            return getattr(imp.S, mxn)(imp.sym_of(node["input"][0]))
        return f
    onnx_op(_onn)(_mk(_mxn))


@onnx_op("Softplus")
def _softplus(imp, node, a):
    return imp.S.Activation(imp.sym_of(node["input"][0]),
                            act_type="softrelu")


for _onn, _mxn in [("Add", "broadcast_add"), ("Sub", "broadcast_sub"),
                   ("Mul", "broadcast_mul"), ("Div", "broadcast_div"),
                   ("Max", "broadcast_maximum"),
                   ("Min", "broadcast_minimum"),
                   ("Pow", "power")]:
    def _mk2(mxn):
        def f(imp, node, a):
            return getattr(imp.S, mxn)(imp.sym_of(node["input"][0]),
                                       imp.sym_of(node["input"][1]))
        return f
    onnx_op(_onn)(_mk2(_mxn))


@onnx_op("MaxPool", "AveragePool")
def _pool(imp, node, a):
    data = imp.sym_of(node["input"][0])
    kernel = a["kernel_shape"]
    kw = dict(kernel=tuple(int(k) for k in kernel),
              stride=tuple(int(s) for s in a.get("strides",
                                                 [1] * len(kernel))),
              pad=tuple(_halve_pads(a.get("pads", [0] * 2 * len(kernel)))),
              pool_type="max" if node["op_type"] == "MaxPool" else "avg")
    if a.get("ceil_mode"):
        kw["pooling_convention"] = "full"
    if node["op_type"] == "AveragePool":
        kw["count_include_pad"] = bool(a.get("count_include_pad", 0))
    return imp.S.Pooling(data, **kw)


@onnx_op("GlobalAveragePool", "GlobalMaxPool")
def _gpool(imp, node, a):
    ptype = "avg" if "Average" in node["op_type"] else "max"
    return imp.S.Pooling(imp.sym_of(node["input"][0]), global_pool=True,
                         pool_type=ptype, kernel=(1, 1))


@onnx_op("Flatten")
def _flatten(imp, node, a):
    if int(a.get("axis", 1)) != 1:
        raise MXNetError("Flatten axis != 1 unsupported")
    return imp.S.Flatten(imp.sym_of(node["input"][0]))


@onnx_op("Reshape")
def _reshape(imp, node, a):
    shape = a.get("shape")
    if shape is None:
        shape = [int(s) for s in imp.const_of(node["input"][1])]
    return imp.S.reshape(imp.sym_of(node["input"][0]),
                         shape=tuple(shape))


@onnx_op("Transpose")
def _transpose(imp, node, a):
    perm = a.get("perm")
    data = imp.sym_of(node["input"][0])
    if perm is None:
        return imp.S.transpose(data)
    return imp.S.transpose(data, axes=tuple(int(p) for p in perm))


@onnx_op("Concat")
def _concat(imp, node, a):
    ins = [imp.sym_of(n) for n in node["input"]]
    return imp.S.concat(*ins, dim=int(a.get("axis", 0)))


@onnx_op("Softmax")
def _softmax(imp, node, a):
    return imp.S.softmax(imp.sym_of(node["input"][0]),
                         axis=int(a.get("axis", -1)))


@onnx_op("LogSoftmax")
def _log_softmax(imp, node, a):
    return imp.S.log_softmax(imp.sym_of(node["input"][0]),
                             axis=int(a.get("axis", -1)))


@onnx_op("Dropout")
def _dropout(imp, node, a):
    return imp.S._copy(imp.sym_of(node["input"][0]))


@onnx_op("LayerNormalization")
def _layernorm(imp, node, a):
    ins = node["input"]
    return imp.S.LayerNorm(imp.sym_of(ins[0]), imp.sym_of(ins[1]),
                           imp.sym_of(ins[2]),
                           axis=int(a.get("axis", -1)),
                           eps=float(a.get("epsilon", 1e-5)))


@onnx_op("Gather")
def _gather(imp, node, a):
    data = imp.sym_of(node["input"][0])
    idx = imp.sym_of(node["input"][1])
    return imp.S.take(data, idx, axis=int(a.get("axis", 0)))


@onnx_op("Cast")
def _cast(imp, node, a):
    np_dt = _DT2NP.get(int(a.get("to", P.DT_FLOAT)), np.float32)
    return imp.S.cast(imp.sym_of(node["input"][0]),
                      dtype=str(np.dtype(np_dt)))


@onnx_op("ReduceMean")
def _reduce_mean(imp, node, a):
    axes = a.get("axes")
    kw = {"keepdims": bool(a.get("keepdims", 1))}
    if axes:
        kw["axis"] = tuple(int(x) for x in axes)
    return imp.S.mean(imp.sym_of(node["input"][0]), **kw)


@onnx_op("Slice")
def _slice(imp, node, a):
    ins = node["input"]
    data = imp.sym_of(ins[0])
    if "starts" in a:  # opset-9 attribute form
        starts, ends = a["starts"], a["ends"]
        axes = a.get("axes", list(range(len(starts))))
    else:
        starts = [int(x) for x in imp.const_of(ins[1])]
        ends = [int(x) for x in imp.const_of(ins[2])]
        axes = [int(x) for x in imp.const_of(ins[3])] if len(ins) > 3 \
            else list(range(len(starts)))
        if len(ins) > 4 and ins[4]:
            steps = [int(x) for x in imp.const_of(ins[4])]
            if any(st != 1 for st in steps):
                raise MXNetError(
                    "ONNX import: Slice steps %s unsupported" % steps)
    out = data
    for ax, b, e in zip(axes, starts, ends):
        e = None if e >= (1 << 60) else int(e)
        out = imp.S.slice_axis(out, axis=int(ax), begin=int(b), end=e)
    return out


@onnx_op("Squeeze")
def _squeeze(imp, node, a):
    ins = node["input"]
    axes = a.get("axes")
    if axes is None and len(ins) > 1:
        axes = [int(x) for x in imp.const_of(ins[1])]
    data = imp.sym_of(ins[0])
    if axes is None:
        return imp.S.squeeze(data)
    return imp.S.squeeze(data, axis=tuple(axes))


@onnx_op("Unsqueeze")
def _unsqueeze(imp, node, a):
    ins = node["input"]
    axes = a.get("axes")
    if axes is None:
        axes = [int(x) for x in imp.const_of(ins[1])]
    out = imp.sym_of(ins[0])
    for ax in sorted(axes):
        out = imp.S.expand_dims(out, axis=int(ax))
    return out


@onnx_op("Clip")
def _clip(imp, node, a):
    ins = node["input"]
    lo = a.get("min")
    hi = a.get("max")
    if lo is None and len(ins) > 1 and ins[1]:
        lo = float(imp.const_of(ins[1]))
    if hi is None and len(ins) > 2 and ins[2]:
        hi = float(imp.const_of(ins[2]))
    return imp.S.clip(imp.sym_of(ins[0]),
                      a_min=float(lo if lo is not None else -3.4e38),
                      a_max=float(hi if hi is not None else 3.4e38))


@onnx_op("Constant")
def _constant(imp, node, a):
    arr = a.get("value")
    if arr is None:
        raise MXNetError("Constant without tensor value")
    name = node["output"][0]
    imp.consts[name] = np.asarray(arr)
    return None  # materialized lazily via sym_of/const_of


def import_model(model_file):
    """Import an ONNX file: returns ``(sym, arg_params, aux_params)``
    (reference: onnx2mx/import_model.py:import_model)."""
    with open(model_file, "rb") as f:
        model = P.decode(f.read(), P.MODEL)
    return import_graph(model["graph"])


def get_model_metadata(model_file):
    """Input/output names+shapes of an ONNX file (reference:
    import_model.py:get_model_metadata)."""
    with open(model_file, "rb") as f:
        model = P.decode(f.read(), P.MODEL)
    g = model["graph"]

    def unpack(vi):
        dims = vi.get("type", {}).get("tensor_type", {}) \
            .get("shape", {}).get("dim", [])
        return (vi["name"], tuple(d.get("dim_value", 0) for d in dims))

    return {
        "input_tensor_data": [unpack(v) for v in g.get("input", [])
                              if v["name"] not in
                              {t["name"] for t in g.get("initializer", [])}],
        "output_tensor_data": [unpack(v) for v in g.get("output", [])],
    }


def import_graph(graph):
    from ...ndarray import array as nd_array

    imp = _Importer()
    for t in graph.get("initializer", []):
        imp.consts[t["name"]] = _tensor_to_np(t)
    init_names = set(imp.consts)
    for vi in graph.get("input", []):
        if vi["name"] in init_names:
            continue
        dims = vi.get("type", {}).get("tensor_type", {}) \
            .get("shape", {}).get("dim", [])
        shape = tuple(int(d.get("dim_value", 0)) for d in dims) or None
        imp.values[vi["name"]] = imp.S.var(vi["name"], shape=shape)

    for node in graph.get("node", []):
        fn = _ONNX2MX.get(node["op_type"])
        if fn is None:
            raise MXNetError(
                "ONNX import: unsupported op %r" % node["op_type"])
        out = fn(imp, node, _attrs(node))
        if out is None:
            continue
        outs = [out] if not isinstance(out, (list, tuple)) else list(out)
        for name, s in zip(node["output"], outs):
            imp.values[name] = s

    out_syms = [imp.values[v["name"]] for v in graph.get("output", [])]
    sym = out_syms[0] if len(out_syms) == 1 \
        else imp.S.Group(out_syms)
    arg_params, aux_params = {}, {}
    for name, arr in imp.params.items():
        (aux_params if name in imp.aux else arg_params)[name] = \
            nd_array(arr)
    return sym, arg_params, aux_params
