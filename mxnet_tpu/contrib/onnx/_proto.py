"""Minimal self-contained ONNX protobuf codec.

The image ships no ``onnx`` package, so this module implements just
enough of the protobuf wire format (varint / 64-bit / length-delimited /
32-bit fields) plus the ONNX message schemas the converter needs:
ModelProto, GraphProto, NodeProto, AttributeProto, TensorProto,
ValueInfoProto / TypeProto.  Field numbers follow the public
``onnx/onnx.proto`` spec (IR version 8 era); files produced here load in
onnxruntime/netron, and models exported by standard tools decode here.

Messages are plain dicts: ``{"name": ..., "graph": {...}}`` with repeated
fields as lists.  Unknown fields are skipped on decode (forward compat).
"""
from __future__ import annotations

import struct

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


def _enc_varint(v):
    out = bytearray()
    if v < 0:
        v += 1 << 64
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field, wire):
    return _enc_varint((field << 3) | wire)


def _enc_field(field, wire, payload):
    if wire == 0:
        return _tag(field, 0) + _enc_varint(payload)
    if wire == 1:
        return _tag(field, 1) + struct.pack("<d", payload)
    if wire == 2:
        if isinstance(payload, str):
            payload = payload.encode()
        return _tag(field, 2) + _enc_varint(len(payload)) + payload
    if wire == 5:
        return _tag(field, 5) + struct.pack("<f", payload)
    raise ValueError(wire)


# ---------------------------------------------------------------------------
# schemas: field number -> (name, kind, [submessage schema])
# kind: int / sint / float32 / double / string / bytes / msg
# repeated fields are marked with a trailing '*'
# ---------------------------------------------------------------------------

DIM = {
    1: ("dim_value", "int"),
    3: ("dim_param", "string"),
}
TENSOR_SHAPE = {1: ("dim*", "msg", DIM)}
TENSOR_TYPE = {
    1: ("elem_type", "int"),
    2: ("shape", "msg", TENSOR_SHAPE),
}
TYPE = {1: ("tensor_type", "msg", TENSOR_TYPE)}
VALUE_INFO = {
    1: ("name", "string"),
    2: ("type", "msg", TYPE),
    3: ("doc_string", "string"),
}
TENSOR = {
    1: ("dims*", "int"),
    2: ("data_type", "int"),
    4: ("float_data*", "float32"),
    5: ("int32_data*", "int"),
    6: ("string_data*", "bytes"),
    7: ("int64_data*", "int"),
    8: ("name", "string"),
    9: ("raw_data", "bytes"),
    10: ("double_data*", "double"),
    11: ("uint64_data*", "int"),
}
ATTRIBUTE = {
    1: ("name", "string"),
    2: ("f", "float32"),
    3: ("i", "int"),
    4: ("s", "bytes"),
    5: ("t", "msg", TENSOR),
    7: ("floats*", "float32"),
    8: ("ints*", "int"),
    9: ("strings*", "bytes"),
    20: ("type", "int"),
}
NODE = {
    1: ("input*", "string"),
    2: ("output*", "string"),
    3: ("name", "string"),
    4: ("op_type", "string"),
    5: ("attribute*", "msg", ATTRIBUTE),
    6: ("doc_string", "string"),
    7: ("domain", "string"),
}
GRAPH = {
    1: ("node*", "msg", NODE),
    2: ("name", "string"),
    5: ("initializer*", "msg", TENSOR),
    10: ("doc_string", "string"),
    11: ("input*", "msg", VALUE_INFO),
    12: ("output*", "msg", VALUE_INFO),
    13: ("value_info*", "msg", VALUE_INFO),
}
OPSET = {
    1: ("domain", "string"),
    2: ("version", "int"),
}
MODEL = {
    1: ("ir_version", "int"),
    2: ("producer_name", "string"),
    3: ("producer_version", "string"),
    4: ("domain", "string"),
    5: ("model_version", "int"),
    6: ("doc_string", "string"),
    7: ("graph", "msg", GRAPH),
    8: ("opset_import*", "msg", OPSET),
}

# attribute type enum (AttributeProto.AttributeType)
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8

# TensorProto.DataType
DT_FLOAT, DT_UINT8, DT_INT8, DT_INT32, DT_INT64 = 1, 2, 3, 6, 7
DT_STRING, DT_BOOL, DT_FLOAT16, DT_DOUBLE = 8, 9, 10, 11
DT_BFLOAT16 = 16

_WIRE_OF = {"int": 0, "sint": 0, "float32": 5, "double": 1,
            "string": 2, "bytes": 2, "msg": 2}


def encode(msg, schema):
    """Encode dict ``msg`` with ``schema`` into protobuf bytes."""
    out = bytearray()
    for field, spec in schema.items():
        name, kind = spec[0], spec[1]
        repeated = name.endswith("*")
        key = name.rstrip("*")
        if key not in msg or msg[key] is None:
            continue
        vals = msg[key] if repeated else [msg[key]]
        wire = _WIRE_OF[kind]
        for v in vals:
            if kind == "msg":
                v = encode(v, spec[2])
            out += _enc_field(field, wire, v)
    return bytes(out)


def decode(buf, schema, pos=0, end=None):
    """Decode protobuf bytes into a dict per ``schema``."""
    if end is None:
        end = len(buf)
    msg = {}
    while pos < end:
        tag, pos = _dec_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        spec = schema.get(field)
        if wire == 0:
            v, pos = _dec_varint(buf, pos)
            if v >= 1 << 63:
                v -= 1 << 64
        elif wire == 1:
            v = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wire == 5:
            v = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        elif wire == 2:
            ln, pos = _dec_varint(buf, pos)
            v = bytes(buf[pos:pos + ln])
            pos += ln
        else:
            raise ValueError("unsupported wire type %d" % wire)
        if spec is None:
            continue  # unknown field: skip
        name, kind = spec[0], spec[1]
        repeated = name.endswith("*")
        key = name.rstrip("*")
        if kind == "msg":
            v = decode(v, spec[2])
        elif kind == "string" and isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        elif kind in ("float32", "double") and wire == 2:
            # packed repeated floats/doubles
            fmt, size = ("<f", 4) if kind == "float32" else ("<d", 8)
            vals = [struct.unpack_from(fmt, v, i)[0]
                    for i in range(0, len(v), size)]
            if repeated:
                msg.setdefault(key, []).extend(vals)
                continue
            v = vals[0]
        elif kind in ("int", "sint") and wire == 2:
            # packed repeated varints
            vals, p2 = [], 0
            while p2 < len(v):
                x, p2 = _dec_varint(v, p2)
                if x >= 1 << 63:
                    x -= 1 << 64
                vals.append(x)
            if repeated:
                msg.setdefault(key, []).extend(vals)
                continue
            v = vals[0] if vals else 0
        if repeated:
            msg.setdefault(key, []).append(v)
        else:
            msg[key] = v
    return msg
