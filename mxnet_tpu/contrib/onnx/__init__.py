"""ONNX interchange (reference parity: ``python/mxnet/contrib/onnx/``).

``mx.contrib.onnx.export_model`` / ``import_model`` /
``get_model_metadata`` — self-contained (in-repo protobuf codec, no
``onnx`` package dependency).
"""
from . import mx2onnx  # noqa: F401
from . import onnx2mx  # noqa: F401
from .mx2onnx import export_model  # noqa: F401
from .onnx2mx import import_model, get_model_metadata  # noqa: F401
