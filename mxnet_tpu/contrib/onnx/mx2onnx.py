"""MXNet Symbol → ONNX export.

Reference parity: ``python/mxnet/contrib/onnx/mx2onnx/export_model.py`` +
``_op_translations.py`` (4.2k LoC of per-op converters).  Same public
API — ``export_model(sym, params, input_shape, ...)`` — rebuilt on the
in-repo protobuf codec (``_proto.py``; the image ships no onnx package).

Graphs export in inference form (Dropout → Identity, BatchNorm uses
moving stats downstream).  Channel-first (NCHW) graphs only — export a
model-zoo net built with ``layout='NCHW'`` (the checkpoint layout); the
NHWC TPU layout is a compile-time optimization, not an interchange
format.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from . import _proto as P

OPSET = 17  # LayerNormalization needs 17

_MX2ONNX = {}


def mx_op(*names):
    def deco(fn):
        for n in names:
            _MX2ONNX[n] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state handed to op translators."""

    def __init__(self, params, shapes):
        self.params = params          # var name -> np.ndarray
        self.shapes = shapes          # value name -> tuple shape
        self.nodes = []               # onnx NodeProto dicts
        self.initializers = {}        # name -> np.ndarray
        self._uid = 0

    def name(self, hint):
        self._uid += 1
        return "%s__%d" % (hint, self._uid)

    def add(self, op_type, inputs, outputs, **attrs):
        node = {"op_type": op_type, "input": list(inputs),
                "output": list(outputs),
                "name": self.name(op_type.lower())}
        if attrs:
            node["attribute"] = [_attr(k, v) for k, v in attrs.items()]
        self.nodes.append(node)
        return outputs[0]

    def tensor(self, hint, arr):
        """Register a constant initializer; returns its value name."""
        name = self.name(hint)
        self.initializers[name] = np.asarray(arr)
        return name


def _attr(name, v):
    if isinstance(v, bool):
        return {"name": name, "i": int(v), "type": P.ATTR_INT}
    if isinstance(v, int):
        return {"name": name, "i": v, "type": P.ATTR_INT}
    if isinstance(v, float):
        return {"name": name, "f": v, "type": P.ATTR_FLOAT}
    if isinstance(v, str):
        return {"name": name, "s": v.encode(), "type": P.ATTR_STRING}
    if isinstance(v, (list, tuple)):
        if v and isinstance(v[0], float):
            return {"name": name, "floats": [float(x) for x in v],
                    "type": P.ATTR_FLOATS}
        return {"name": name, "ints": [int(x) for x in v],
                "type": P.ATTR_INTS}
    raise MXNetError("unsupported attribute %s=%r" % (name, v))


_NP2DT = {"float32": P.DT_FLOAT, "float64": P.DT_DOUBLE,
          "float16": P.DT_FLOAT16, "int32": P.DT_INT32,
          "int64": P.DT_INT64, "int8": P.DT_INT8, "uint8": P.DT_UINT8,
          "bool": P.DT_BOOL, "bfloat16": P.DT_BFLOAT16}


def _tensor_proto(name, arr):
    arr = np.asarray(arr)
    dt = _NP2DT.get(str(arr.dtype))
    if dt is None:
        raise MXNetError("cannot export dtype %s" % arr.dtype)
    if str(arr.dtype) == "bfloat16":
        raw = arr.view(np.uint16).tobytes()
    else:
        raw = arr.tobytes()
    return {"name": name, "dims": list(arr.shape), "data_type": dt,
            "raw_data": raw}


def _value_info(name, shape, elem_type=P.DT_FLOAT):
    return {"name": name,
            "type": {"tensor_type": {
                "elem_type": elem_type,
                "shape": {"dim": [{"dim_value": int(d)} for d in shape]}}}}


# ---------------------------------------------------------------------------
# helpers shared by translators
# ---------------------------------------------------------------------------


def _get_weightT(ctx, wname):
    """Return name of W^T: pre-transposed initializer when W is constant,
    else a Transpose node."""
    if wname in ctx.initializers:
        arr = ctx.initializers[wname]
        return ctx.tensor(wname + "_T", np.ascontiguousarray(arr.T))
    return ctx.add("Transpose", [wname], [ctx.name(wname + "_T")],
                   perm=[1, 0])


# ---------------------------------------------------------------------------
# translators
# ---------------------------------------------------------------------------


@mx_op("Convolution")
def _conv(ctx, ins, outs, a):
    layout = a.get("layout", "NCHW")
    if not str(layout).startswith("NC"):
        raise MXNetError(
            "ONNX export supports channel-first graphs only; rebuild the "
            "net with layout='NCHW' (got %s)" % layout)
    kernel = [int(k) for k in a["kernel"]]
    attrs = dict(kernel_shape=kernel,
                 strides=[int(s) for s in a.get("stride") or [1] * len(kernel)],
                 dilations=[int(d) for d in a.get("dilate") or [1] * len(kernel)],
                 group=int(a.get("num_group", 1)))
    pad = [int(p) for p in a.get("pad") or [0] * len(kernel)]
    attrs["pads"] = pad + pad
    inputs = ins[:2] if _true(a.get("no_bias")) else ins[:3]
    ctx.add("Conv", inputs, outs, **attrs)


def _true(v):
    return v in (True, 1, "True", "true", "1")


@mx_op("BatchNorm")
def _bn(ctx, ins, outs, a):
    # ins: data gamma beta moving_mean moving_var; out 0 only (inference)
    gamma = ins[1]
    if _true(a.get("fix_gamma", True)) and gamma in ctx.initializers:
        ctx.initializers[gamma] = np.ones_like(ctx.initializers[gamma])
    ctx.add("BatchNormalization", ins[:5], [outs[0]],
            epsilon=float(a.get("eps", 1e-3)),
            momentum=float(a.get("momentum", 0.9)))


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@mx_op("Activation")
def _act(ctx, ins, outs, a):
    ctx.add(_ACT[a.get("act_type", "relu")], ins[:1], outs)


@mx_op("relu")
def _relu(ctx, ins, outs, a):
    ctx.add("Relu", ins[:1], outs)


@mx_op("sigmoid")
def _sigmoid(ctx, ins, outs, a):
    ctx.add("Sigmoid", ins[:1], outs)


@mx_op("tanh")
def _tanh(ctx, ins, outs, a):
    ctx.add("Tanh", ins[:1], outs)


for _mxn, _onn in [("erf", "Erf"), ("sqrt", "Sqrt"), ("exp", "Exp"),
                   ("log", "Log"), ("negative", "Neg"), ("abs", "Abs"),
                   ("floor", "Floor"), ("ceil", "Ceil"),
                   ("sin", "Sin"), ("cos", "Cos")]:
    def _mk(onn):
        def f(ctx, ins, outs, a):
            ctx.add(onn, ins[:1], outs)
        return f
    mx_op(_mxn)(_mk(_onn))


@mx_op("Pooling")
def _pool(ctx, ins, outs, a):
    ptype = a.get("pool_type", "max")
    if _true(a.get("global_pool")):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.add(op, ins[:1], outs)
        return
    kernel = [int(k) for k in a["kernel"]]
    attrs = dict(
        kernel_shape=kernel,
        strides=[int(s) for s in a.get("stride") or [1] * len(kernel)])
    pad = [int(p) for p in a.get("pad") or [0] * len(kernel)]
    attrs["pads"] = pad + pad
    if a.get("pooling_convention") == "full":
        attrs["ceil_mode"] = 1
    if ptype == "avg":
        attrs["count_include_pad"] = int(
            _true(a.get("count_include_pad", True)))
        ctx.add("AveragePool", ins[:1], outs, **attrs)
    elif ptype == "max":
        ctx.add("MaxPool", ins[:1], outs, **attrs)
    else:
        raise MXNetError("Pooling %s not exportable" % ptype)


@mx_op("FullyConnected")
def _fc(ctx, ins, outs, a):
    no_bias = _true(a.get("no_bias"))
    data, w = ins[0], ins[1]
    bias = None if no_bias else ins[2]
    flatten = _true(a.get("flatten", True))
    dshape = ctx.shapes.get(data)
    if flatten and dshape is not None and len(dshape) != 2:
        data = ctx.add("Flatten", [data], [ctx.name("flatten")], axis=1)
        dshape = (dshape[0], int(np.prod(dshape[1:])))
    if dshape is not None and len(dshape) == 2:
        inputs = [data, w] + ([bias] if bias else [])
        ctx.add("Gemm", inputs, outs, alpha=1.0, beta=1.0,
                transA=0, transB=1)
        return
    # N-D, flatten=False: MatMul with W^T (+ Add bias)
    wT = _get_weightT(ctx, w)
    mm = ctx.add("MatMul", [data, wT],
                 [outs[0] if bias is None else ctx.name("matmul")])
    if bias is not None:
        ctx.add("Add", [mm, bias], outs)


@mx_op("elemwise_add", "broadcast_add", "_plus", "_add")
def _add(ctx, ins, outs, a):
    ctx.add("Add", ins[:2], outs)


@mx_op("elemwise_sub", "broadcast_sub", "_sub", "_minus")
def _sub(ctx, ins, outs, a):
    ctx.add("Sub", ins[:2], outs)


@mx_op("elemwise_mul", "broadcast_mul", "_mul")
def _mul(ctx, ins, outs, a):
    ctx.add("Mul", ins[:2], outs)


@mx_op("elemwise_div", "broadcast_div", "_div")
def _div(ctx, ins, outs, a):
    ctx.add("Div", ins[:2], outs)


@mx_op("broadcast_maximum", "maximum")
def _max2(ctx, ins, outs, a):
    ctx.add("Max", ins[:2], outs)


@mx_op("broadcast_minimum", "minimum")
def _min2(ctx, ins, outs, a):
    ctx.add("Min", ins[:2], outs)


def _scalar_of(ctx, ins, a):
    dt = np.float32
    return ctx.tensor("scalar", np.array(float(a.get("scalar", 0.0)), dt))


@mx_op("_plus_scalar")
def _plus_scalar(ctx, ins, outs, a):
    ctx.add("Add", [ins[0], _scalar_of(ctx, ins, a)], outs)


@mx_op("_minus_scalar")
def _minus_scalar(ctx, ins, outs, a):
    ctx.add("Sub", [ins[0], _scalar_of(ctx, ins, a)], outs)


@mx_op("_rminus_scalar")
def _rminus_scalar(ctx, ins, outs, a):
    ctx.add("Sub", [_scalar_of(ctx, ins, a), ins[0]], outs)


@mx_op("_mul_scalar")
def _mul_scalar(ctx, ins, outs, a):
    ctx.add("Mul", [ins[0], _scalar_of(ctx, ins, a)], outs)


@mx_op("_div_scalar")
def _div_scalar(ctx, ins, outs, a):
    ctx.add("Div", [ins[0], _scalar_of(ctx, ins, a)], outs)


@mx_op("_rdiv_scalar")
def _rdiv_scalar(ctx, ins, outs, a):
    ctx.add("Div", [_scalar_of(ctx, ins, a), ins[0]], outs)


@mx_op("_power_scalar")
def _power_scalar(ctx, ins, outs, a):
    ctx.add("Pow", [ins[0], _scalar_of(ctx, ins, a)], outs)


@mx_op("square")
def _square(ctx, ins, outs, a):
    ctx.add("Mul", [ins[0], ins[0]], outs)


@mx_op("reshape", "Reshape")
def _reshape(ctx, ins, outs, a):
    shape = [int(s) for s in a.get("shape", ())]
    if any(d < -1 for d in shape):
        # MXNet's -2/-3/-4 split/merge codes have no ONNX equivalent
        raise MXNetError(
            "ONNX export: reshape special codes %s unsupported "
            "(only 0 and -1 translate)" % (shape,))
    sname = ctx.tensor("shape", np.asarray(shape, np.int64))
    ctx.add("Reshape", [ins[0], sname], outs)


@mx_op("Flatten", "flatten")
def _flatten(ctx, ins, outs, a):
    ctx.add("Flatten", ins[:1], outs, axis=1)


@mx_op("transpose")
def _transpose(ctx, ins, outs, a):
    axes = a.get("axes")
    if axes:
        ctx.add("Transpose", ins[:1], outs, perm=[int(x) for x in axes])
    else:
        ctx.add("Transpose", ins[:1], outs)


@mx_op("concat", "Concat")
def _concat(ctx, ins, outs, a):
    ctx.add("Concat", ins, outs, axis=int(a.get("dim", 1)))


@mx_op("softmax")
def _softmax(ctx, ins, outs, a):
    ctx.add("Softmax", ins[:1], outs, axis=int(a.get("axis", -1)))


@mx_op("log_softmax")
def _log_softmax(ctx, ins, outs, a):
    sm = ctx.add("Softmax", ins[:1], [ctx.name("softmax")],
                 axis=int(a.get("axis", -1)))
    ctx.add("Log", [sm], outs)


@mx_op("Dropout")
def _dropout(ctx, ins, outs, a):
    ctx.add("Identity", ins[:1], [outs[0]])


@mx_op("_copy", "identity", "BlockGrad", "stop_gradient")
def _identity(ctx, ins, outs, a):
    ctx.add("Identity", ins[:1], [outs[0]])


@mx_op("LayerNorm")
def _layernorm(ctx, ins, outs, a):
    ctx.add("LayerNormalization", ins[:3], [outs[0]],
            axis=int(a.get("axis", -1)),
            epsilon=float(a.get("eps", 1e-5)))


@mx_op("Embedding")
def _embedding(ctx, ins, outs, a):
    idx = ctx.add("Cast", [ins[0]], [ctx.name("cast")], to=P.DT_INT64)
    ctx.add("Gather", [ins[1], idx], outs, axis=0)


@mx_op("dot")
def _dot(ctx, ins, outs, a):
    x, y = ins[0], ins[1]
    if _true(a.get("transpose_a")):
        x = ctx.add("Transpose", [x], [ctx.name("ta")], perm=[1, 0])
    if _true(a.get("transpose_b")):
        y = ctx.add("Transpose", [y], [ctx.name("tb")], perm=[1, 0])
    ctx.add("MatMul", [x, y], outs)


@mx_op("batch_dot")
def _batch_dot(ctx, ins, outs, a):
    x, y = ins[0], ins[1]
    if _true(a.get("transpose_a")):
        x = ctx.add("Transpose", [x], [ctx.name("ta")], perm=[0, 2, 1])
    if _true(a.get("transpose_b")):
        y = ctx.add("Transpose", [y], [ctx.name("tb")], perm=[0, 2, 1])
    ctx.add("MatMul", [x, y], outs)


@mx_op("mean")
def _mean(ctx, ins, outs, a):
    axis = a.get("axis")
    attrs = {"keepdims": int(_true(a.get("keepdims")))}
    if axis is not None and axis != ():
        axes = [int(axis)] if isinstance(axis, int) else \
            [int(x) for x in axis]
        attrs["axes"] = axes
    ctx.add("ReduceMean", ins[:1], outs, **attrs)


@mx_op("slice_axis")
def _slice_axis(ctx, ins, outs, a):
    axis = int(a.get("axis", 0))
    begin = int(a.get("begin", 0))
    end = a.get("end")
    end = int(end) if end is not None else (1 << 62)
    ctx.add("Slice", [
        ins[0],
        ctx.tensor("starts", np.asarray([begin], np.int64)),
        ctx.tensor("ends", np.asarray([end], np.int64)),
        ctx.tensor("axes", np.asarray([axis], np.int64)),
    ], outs)


@mx_op("squeeze")
def _squeeze(ctx, ins, outs, a):
    axis = a.get("axis")
    if axis is None:
        ctx.add("Squeeze", ins[:1], outs)
        return
    axes = [int(axis)] if isinstance(axis, int) else [int(x) for x in axis]
    ctx.add("Squeeze",
            [ins[0], ctx.tensor("axes", np.asarray(axes, np.int64))], outs)


@mx_op("expand_dims")
def _expand_dims(ctx, ins, outs, a):
    ctx.add("Unsqueeze", [
        ins[0],
        ctx.tensor("axes", np.asarray([int(a.get("axis", 0))], np.int64)),
    ], outs)


@mx_op("clip")
def _clip(ctx, ins, outs, a):
    ctx.add("Clip", [
        ins[0],
        ctx.tensor("min", np.array(float(a.get("a_min")), np.float32)),
        ctx.tensor("max", np.array(float(a.get("a_max")), np.float32)),
    ], outs)


@mx_op("Cast", "cast")
def _cast(ctx, ins, outs, a):
    dt = _NP2DT[str(np.dtype(a.get("dtype", "float32")))]
    ctx.add("Cast", ins[:1], outs, to=dt)


@mx_op("_contrib_flash_attention")
def _flash(ctx, ins, outs, a):
    """Decompose fused attention into MatMul/Softmax/MatMul (the ONNX
    graph materializes scores — interchange form, not the TPU kernel)."""
    q, k, v = ins[0], ins[1], ins[2]
    qshape = ctx.shapes.get(q)
    if qshape is None:
        raise MXNetError("flash_attention export needs static shapes")
    d = int(qshape[-1])
    t_q = int(qshape[-2])
    scale = a.get("scale")
    scale = float(scale) if scale else 1.0 / float(np.sqrt(d))
    rank = len(qshape)
    perm = list(range(rank - 2)) + [rank - 1, rank - 2]
    kT = ctx.add("Transpose", [k], [ctx.name("kT")], perm=perm)
    s = ctx.add("MatMul", [q, kT], [ctx.name("scores")])
    s = ctx.add("Mul", [s, ctx.tensor("scale",
                                      np.array(scale, np.float32))],
                [ctx.name("scaled")])
    if _true(a.get("causal")):
        mask = np.triu(np.full((t_q, t_q), -1e9, np.float32), k=1)
        s = ctx.add("Add", [s, ctx.tensor("causal_mask", mask)],
                    [ctx.name("masked")])
    p = ctx.add("Softmax", [s], [ctx.name("probs")], axis=-1)
    ctx.add("MatMul", [p, v], outs)


# ---------------------------------------------------------------------------
# graph walk
# ---------------------------------------------------------------------------


def _node_shapes(sym, input_shapes):
    """Static shape for every value in the graph via one abstract eval."""
    import jax

    from ...ops import registry as _reg

    nodes = sym._topo_nodes()
    shapes = {}

    def walk(bindings):
        vals = {}
        for node in nodes:
            if node.is_variable:
                vals[id(node)] = (bindings[node.name],)
                continue
            from ...symbol.symbol import _op_attrs

            reg = _reg.get(node.op)
            ins = [vals[id(inp)][idx] for inp, idx in node.inputs]
            attrs = _op_attrs(node, "predict" if reg.needs_mode else None)
            if reg.needs_rng:
                ins = [jax.random.PRNGKey(0)] + ins
            out = reg.forward(*ins, **attrs)
            vals[id(node)] = out if isinstance(out, tuple) else (out,)
        return vals

    bindings = {n: jax.ShapeDtypeStruct(tuple(s), np.float32)
                for n, s in input_shapes.items()}

    def capture(bindings):
        vals = walk(bindings)
        return tuple(v for node in nodes for v in vals[id(node)])

    outs = jax.eval_shape(capture, bindings)
    i = 0
    for node in nodes:
        n_out = 1 if node.is_variable else node.num_outputs
        for k in range(n_out):
            shapes[_value_name(node, k)] = tuple(outs[i].shape)
            i += 1
    return shapes


def _value_name(node, idx=0):
    if node.is_variable:
        return node.name
    if node.num_outputs == 1:
        return node.name
    return "%s_out%d" % (node.name, idx)


def export_model(sym, params, input_shape=None, input_type=None,
                 onnx_file_path="model.onnx", verbose=False,
                 input_names=None, model_name="mxnet_tpu_model"):
    """Export a Symbol + params to an ONNX file (reference:
    contrib/onnx/mx2onnx/export_model.py:export_model).

    Parameters
    ----------
    sym : Symbol (single- or multi-output)
    params : dict name -> NDArray/np.ndarray (arg + aux merged)
    input_shape : list of tuples, one per graph input (non-param vars,
        in list_inputs order)
    onnx_file_path : destination; also returns the path
    """
    from ...ndarray.ndarray import NDArray

    np_params = {}
    for k, v in (params or {}).items():
        k = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        np_params[k] = v.asnumpy() if isinstance(v, NDArray) \
            else np.asarray(v)

    nodes = sym._topo_nodes()
    in_vars = [n for n in nodes if n.is_variable
               and n.name not in np_params]
    if input_shape is not None:
        if len(input_shape) != len(in_vars):
            raise MXNetError(
                "input_shape: expected %d shapes for inputs %s"
                % (len(in_vars), [n.name for n in in_vars]))
        input_shapes = {n.name: tuple(s)
                        for n, s in zip(in_vars, input_shape)}
    else:
        input_shapes = {}
        for n in in_vars:
            if "__shape__" not in n.attrs:
                raise MXNetError(
                    "input %r has no shape; pass input_shape=" % n.name)
            input_shapes[n.name] = tuple(n.attrs["__shape__"])
    for n in nodes:
        if n.is_variable and n.name in np_params:
            input_shapes[n.name] = tuple(np_params[n.name].shape)

    shapes = _node_shapes(sym, input_shapes)
    ctx = _Ctx(np_params, shapes)
    for name, arr in np_params.items():
        ctx.initializers[name] = arr

    for node in nodes:
        if node.is_variable:
            continue
        fn = _MX2ONNX.get(node.op)
        if fn is None:
            raise MXNetError(
                "ONNX export: no translator for op %r" % node.op)
        ins = [_value_name(inp, idx) for inp, idx in node.inputs]
        outs = [_value_name(node, k) for k in range(node.num_outputs)]
        fn(ctx, ins, outs, dict(node.attrs))

    out_names = [_value_name(n, i) for n, i in sym._outputs]
    graph = {
        "name": model_name,
        "node": ctx.nodes,
        "initializer": [_tensor_proto(k, v)
                        for k, v in ctx.initializers.items()],
        "input": [_value_info(n.name, input_shapes[n.name])
                  for n in in_vars],
        "output": [_value_info(n, shapes.get(n, ()))
                   for n in out_names],
    }
    model = {
        "ir_version": 8,
        "producer_name": "mxnet_tpu",
        "producer_version": "0.1",
        "opset_import": [{"domain": "", "version": OPSET}],
        "graph": graph,
    }
    data = P.encode(model, P.MODEL)
    with open(onnx_file_path, "wb") as f:
        f.write(data)
    if verbose:
        print("exported %d nodes, %d initializers -> %s"
              % (len(ctx.nodes), len(ctx.initializers), onnx_file_path))
    return onnx_file_path
