"""INT8 quantization (parity: python/mxnet/contrib/quantization.py).

Reference mechanism: a graph pass inserts quantize/dequantize/requantize
around supported ops (``quantize_graph_pass.cc``), calibrated over a
dataset by min/max ("naive") or KL-divergence thresholds ("entropy",
``calibrate.cc``), executed by MKL-DNN/cuDNN int8 kernels.

TPU-native mechanism: ``quantize_net`` walks a Gluon network and swaps
Dense/Conv2D blocks for int8 equivalents whose matmul runs as an int8×int8
``dot_general`` with int32 accumulation — the MXU's native int8 mode —
then dequantizes with the calibrated scales.  ``quantize_model`` /
``quantize_graph`` (the symbolic API) rewrite the Symbol with
fake-quantize nodes (quantize→dequantize in f32): bit-identical numerics
to the int8 path for calibration/accuracy work, while the int8 *speed*
path is the Gluon converter (documented deviation: XLA fuses the symbolic
graph itself, so a symbol-level int8 op swap would not change the kernels
XLA picks).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .. import autograd
from ..ndarray.ndarray import NDArray
from ..gluon import nn as _nn
from ..gluon.block import HybridBlock


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def _kl_divergence(p, q):
    p = p / max(p.sum(), 1e-12)
    q = q / max(q.sum(), 1e-12)
    mask = p > 0
    return float(np.sum(p[mask] * np.log(p[mask] /
                                         np.maximum(q[mask], 1e-12))))


def _get_optimal_threshold(samples, num_bins=1001, num_quantized_bins=255):
    """KL-optimal |threshold| for int8 (parity: calibrate.cc
    GetOptimalThreshold — minimize KL(P||Q) over truncation points)."""
    arr = np.abs(np.concatenate([np.asarray(s).ravel() for s in samples]))
    max_val = float(arr.max()) if arr.size else 1.0
    if max_val == 0.0:
        return 1e-8
    hist, edges = np.histogram(arr, bins=num_bins, range=(0, max_val))
    best_kl, best_t = None, max_val
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, (num_bins - num_quantized_bins) // 64)):
        threshold = edges[i] if i < len(edges) else max_val
        sliced = hist[:i].astype(np.float64)
        if sliced.size == 0:
            continue
        # P: clipped distribution — outlier mass folds into the last bin;
        # Q: the QUANTIZED version of the unclipped slice.  Building Q
        # without the outliers is what makes KL punish aggressive
        # truncation (reference calibrate.cc / TensorRT formulation).
        p = sliced.copy()
        p[-1] += hist[i:].sum()
        factor = sliced.size / num_quantized_bins
        q = np.zeros_like(sliced)
        for j in range(num_quantized_bins):
            lo = int(j * factor)
            hi = int((j + 1) * factor) if j < num_quantized_bins - 1 \
                else sliced.size
            chunk = sliced[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        kl = _kl_divergence(p, q)
        if best_kl is None or kl < best_kl:
            best_kl, best_t = kl, threshold
    return max(best_t, 1e-8)


class _Calibrator:
    """Collect per-layer input ranges over calibration batches."""

    def __init__(self, mode="naive"):
        if mode not in ("naive", "entropy"):
            raise MXNetError("calib_mode must be naive or entropy")
        self.mode = mode
        self.samples = {}

    def observe(self, name, arr):
        a = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
        self.samples.setdefault(name, []).append(a)

    def threshold(self, name):
        samples = self.samples.get(name)
        if not samples:
            return 1.0
        if self.mode == "naive":
            return max(float(np.abs(s).max()) for s in samples) or 1e-8
        return _get_optimal_threshold(samples)


# ---------------------------------------------------------------------------
# int8 blocks
# ---------------------------------------------------------------------------

def _quant_params(threshold):
    # symmetric int8: scale maps [-t, t] → [-127, 127]
    return 127.0 / float(threshold)


class QuantizedDense(HybridBlock):
    """Dense with int8 weights/activations, int32 MXU accumulation."""

    def __init__(self, dense, act_threshold, prefix=None):
        super().__init__(prefix=prefix)
        w = dense.weight.data().asnumpy()
        self._w_scale = _quant_params(np.abs(w).max() or 1e-8)
        self._w_q = jnp.asarray(
            np.clip(np.round(w * self._w_scale), -127, 127), jnp.int8)
        self._x_scale = _quant_params(act_threshold)
        self._bias = None
        if getattr(dense, "bias", None) is not None:
            self._bias = jnp.asarray(dense.bias.data().asnumpy())
        self._flatten = getattr(dense, "_flatten", True)
        self._act = getattr(dense, "act", None)

    def hybrid_forward(self, F, x):
        from ..ops.registry import invoke_fn

        w_q, w_scale, x_scale, bias = (self._w_q, self._w_scale,
                                       self._x_scale, self._bias)
        flatten = self._flatten

        def fn(raw):
            flat = raw.reshape(raw.shape[0], -1) if flatten else raw
            xq = jnp.clip(jnp.round(flat * x_scale), -127, 127) \
                .astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, w_q, (((flat.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) / (x_scale * w_scale)
            if bias is not None:
                out = out + bias
            return (out,)

        (out,) = invoke_fn(fn, [x], op_name="quantized_dense")
        if self._act is not None:
            out = self._act(out)
        return out


class QuantizedConv2D(HybridBlock):
    """Conv2D with int8 weights/activations, int32 accumulation."""

    def __init__(self, conv, act_threshold, prefix=None):
        super().__init__(prefix=prefix)
        w = conv.weight.data().asnumpy()
        self._w_scale = _quant_params(np.abs(w).max() or 1e-8)
        self._w_q = jnp.asarray(
            np.clip(np.round(w * self._w_scale), -127, 127), jnp.int8)
        self._x_scale = _quant_params(act_threshold)
        self._bias = None
        if getattr(conv, "bias", None) is not None:
            self._bias = jnp.asarray(conv.bias.data().asnumpy())
        self._opkw = dict(conv._kwargs)
        self._act = getattr(conv, "act", None)

    def hybrid_forward(self, F, x):
        from ..ops.registry import invoke_fn
        from ..ops.nn import _CONV_DIMNUMS, _as_tuple

        w_q, w_scale, x_scale, bias = (self._w_q, self._w_scale,
                                       self._x_scale, self._bias)
        kw = self._opkw
        layout = kw.get("layout", "NCHW")

        def fn(raw):
            nd_ = w_q.ndim - 2
            st = _as_tuple(kw.get("stride") or (1,) * nd_, nd_)
            pd = _as_tuple(kw.get("pad") or (0,) * nd_, nd_)
            xq = jnp.clip(jnp.round(raw * x_scale), -127, 127) \
                .astype(jnp.int8)
            dn = jax.lax.conv_dimension_numbers(
                raw.shape, w_q.shape, _CONV_DIMNUMS[layout])
            acc = jax.lax.conv_general_dilated(
                xq, w_q, window_strides=st,
                padding=[(p, p) for p in pd],
                dimension_numbers=dn,
                feature_group_count=kw.get("num_group", 1),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) / (x_scale * w_scale)
            if bias is not None:
                if layout != "NCHW" and layout.endswith("C"):
                    out = out + bias
                else:
                    out = out + bias.reshape((1, -1) + (1,) * nd_)
            return (out,)

        (out,) = invoke_fn(fn, [x], op_name="quantized_conv")
        if self._act is not None:
            out = self._act(out)
        return out


_QUANTIZABLE = {}


def _register_quantizable():
    _QUANTIZABLE[_nn.Dense] = QuantizedDense
    _QUANTIZABLE[_nn.Conv2D] = QuantizedConv2D


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def quantize_net_v2(network, quantized_dtype="int8", calib_mode="naive",
                    calib_data=None, num_calib_batches=None,
                    exclude_layers=None, **kwargs):
    """Quantize a Gluon net in place (parity: quantization.py:826).

    Runs ``calib_data`` through the net observing each quantizable
    layer's input range (naive min/max or KL-entropy threshold), then
    replaces Dense/Conv2D children with int8 blocks.
    """
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError("quantized_dtype must be int8 (TPU MXU mode)")
    if calib_data is None:
        raise MXNetError("calib_data is required")
    _register_quantizable()
    exclude = set(exclude_layers or ())

    # find quantizable sub-blocks and hook their inputs
    targets = []

    def walk(block, path):
        for name, child in list(block._children.items()):
            full = "%s.%s" % (path, name) if path else name
            if type(child) in _QUANTIZABLE and full not in exclude \
                    and child.name not in exclude:
                targets.append((block, name, full, child))
            else:
                walk(child, full)

    walk(network, "")
    if not targets:
        raise MXNetError("no quantizable layers found")

    calib = _Calibrator(calib_mode)
    hooked = []
    for _, _, full, child in targets:
        orig = child.hybrid_forward

        def make_spy(full_name, block, orig_fn):
            def spy(F, x, *a, **kw):
                calib.observe(full_name, x)
                return orig_fn(F, x, *a, **kw)
            return spy

        child.hybrid_forward = make_spy(full, child, orig)
        hooked.append((child, orig))

    n = 0
    with autograd.predict_mode():
        for batch in calib_data:
            data = batch[0] if isinstance(batch, (list, tuple)) else batch
            network(data if isinstance(data, NDArray) else NDArray(data))
            n += 1
            if num_calib_batches is not None and n >= num_calib_batches:
                break
    for child, orig in hooked:
        child.hybrid_forward = orig

    for parent, name, full, child in targets:
        qcls = _QUANTIZABLE[type(child)]
        parent._children[name] = qcls(child, calib.threshold(full))
        try:
            setattr(parent, name, parent._children[name])
        except Exception:
            pass
    return network


def quantize_net(network, **kwargs):
    return quantize_net_v2(network, **kwargs)


def quantize_graph(sym, arg_params, aux_params, th_dict=None,
                   excluded_sym_names=None, quantized_dtype="int8",
                   **kwargs):
    """Symbol rewrite inserting fake-quantize around FC/Conv inputs
    (parity: quantization.py:651).  Numerics match the int8 path;
    see module docstring for the TPU execution story."""
    from .. import sym as _sym

    th_dict = th_dict or {}
    excluded = set(excluded_sym_names or ())

    def fake_quant(s, threshold):
        scale = 127.0 / max(float(threshold), 1e-8)
        q = _sym.clip(_sym.round(s * scale), -127.0, 127.0)
        return q / scale

    # rebuild the graph bottom-up
    from ..symbol.symbol import Symbol, _Node

    memo = {}

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        if node.is_variable:
            memo[id(node)] = node
            return node
        new_inputs = []
        for inp, idx in node.inputs:
            new_inputs.append((rebuild(inp), idx))
        nn_node = _Node(node.op, node.name, dict(node.attrs),
                        new_inputs, node.num_outputs)
        if node.op in ("FullyConnected", "Convolution") \
                and node.name not in excluded:
            # wrap data+weight entries in fake-quant subgraphs; the
            # threshold belongs to the PRODUCER of each input (the
            # calibrated tensor), weights use their exact |max|
            wrapped = []
            for j, (inp, idx) in enumerate(new_inputs):
                if j <= 1:  # data, weight
                    pname = inp.name
                    if pname in arg_params:
                        import numpy as _np

                        t = float(_np.abs(
                            arg_params[pname].asnumpy()).max()) or 1e-8
                    else:
                        t = th_dict.get(
                            pname, th_dict.get(pname + "_output", 1.0))
                    s_in = Symbol([(inp, idx)])
                    fq = fake_quant(s_in, t)
                    wrapped.append(fq._outputs[0])
                else:
                    wrapped.append((inp, idx))
            nn_node.inputs = wrapped
        memo[id(node)] = nn_node
        return nn_node

    heads = [(rebuild(n), i) for n, i in sym._outputs]
    return Symbol(heads), arg_params, aux_params


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", **kwargs):
    """Parity: quantization.py:463.  Calibrates thresholds by evaluating
    the symbol over calib_data, then applies ``quantize_graph``."""
    th_dict = {}
    if calib_data is not None:
        exe_inputs = {}
        # naive per-head-input calibration: run forward, record FC/Conv
        # input magnitudes via the internals
        internals = sym.get_internals()
        seen = 0
        samples = {}
        for batch in calib_data:
            data = batch[0] if isinstance(batch, (list, tuple)) else batch
            arr = data if isinstance(data, NDArray) else NDArray(data)
            exe_inputs[data_names[0]] = arr
            bindings = dict(exe_inputs)
            for name, value in arg_params.items():
                bindings[name] = value
            for name, value in (aux_params or {}).items():
                bindings[name] = value
            outs = internals.eval_imperative(bindings)
            for name, out in zip(internals.list_outputs(), outs):
                samples.setdefault(name, []).append(out.asnumpy())
            seen += arr.shape[0]
            if num_calib_examples is not None and \
                    seen >= num_calib_examples:
                break
        for name, arrs in samples.items():
            if calib_mode == "entropy":
                th_dict[name] = _get_optimal_threshold(arrs)
            else:
                th_dict[name] = max(float(np.abs(a).max()) for a in arrs) \
                    or 1e-8
    qsym, qarg, qaux = quantize_graph(
        sym, arg_params, aux_params, th_dict=th_dict,
        excluded_sym_names=excluded_sym_names,
        quantized_dtype=quantized_dtype)
    return qsym, qarg, qaux
