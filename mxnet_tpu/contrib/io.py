"""Contrib IO: gluon↔Module bridges (parity: contrib/io.py).

``DataLoaderIter`` wraps a ``gluon.data.DataLoader`` as a classic
``DataIter`` so gluon data pipelines feed the symbolic Module API.
"""
from __future__ import annotations

import numpy as np

from ..io.io import DataBatch, DataDesc, DataIter


class DataLoaderIter(DataIter):
    """Adapt a gluon DataLoader to the DataIter interface (parity:
    contrib/io.py:25).  Each loader item must be a (data, label) pair;
    shapes are probed from the first batch."""

    def __init__(self, loader, data_name="data",
                 label_name="softmax_label", dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        data, label = next(self._iter)
        self.batch_size = int(data.shape[0])
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, tuple(data.shape), dtype)]
        self.provide_label = [
            DataDesc(label_name, tuple(label.shape), dtype)]
        self._current_batch = (data, label)

    def reset(self):
        self._iter = iter(self._loader)
        self._current_batch = None

    def next(self):
        if self._current_batch is None:
            try:
                self._current_batch = next(self._iter)
            except StopIteration:
                raise StopIteration
        data, label = self._current_batch
        self._current_batch = None
        from .. import nd

        def as_nd(x):
            if hasattr(x, "asnumpy"):
                return x
            return nd.array(np.asarray(x))

        return DataBatch(data=[as_nd(data)], label=[as_nd(label)], pad=0)
