"""Legacy contrib autograd API (parity: contrib/autograd.py).

The reference kept a deprecated pre-1.0 autograd surface under
``mx.contrib.autograd`` (``set_is_training``, ``TrainingStateScope``,
``train_section``/``test_section``, ``compute_gradient``,
``backward``).  They delegate to the modern tape here.
"""
from __future__ import annotations

from .. import autograd as _ag


def set_is_training(is_train):
    """Flip recording+training mode; returns the previous record flag
    (parity: contrib/autograd.py set_is_training — which set BOTH the
    training and recording flags)."""
    prev = _ag.is_recording()
    _ag.set_recording(is_train)
    _ag.set_training(is_train)
    return prev


def _get_state():
    return (_ag.is_recording(), _ag.is_training())


def _set_state(state):
    _ag.set_recording(state[0])
    _ag.set_training(state[1])


class TrainingStateScope:
    """``with TrainingStateScope(True): ...`` (parity:
    contrib/autograd.py:54).  Saves and restores BOTH the recording and
    training flags — ``set_is_training`` mutates both, so restoring
    only on a recording-flag mismatch (as a naive port would) can leave
    the training flag permanently flipped inside an outer
    ``record(train_mode=False)`` scope."""

    def __init__(self, enter_state):
        self._enter_state = bool(enter_state)
        self._prev = None

    def __enter__(self):
        self._prev = _get_state()
        set_is_training(self._enter_state)
        return self

    def __exit__(self, ptype, value, trace):
        _set_state(self._prev)
        return False


def train_section():
    """Training scope for ``with`` (parity: train_section)."""
    return TrainingStateScope(True)


def test_section():
    """Prediction scope for ``with`` (parity: test_section)."""
    return TrainingStateScope(False)


def backward(outputs, out_grads=None, retain_graph=False):
    """Legacy multi-output backward (parity: contrib backward)."""
    _ag.backward(outputs, head_grads=out_grads,
                 retain_graph=retain_graph)


def compute_gradient(outputs):
    """Deprecated — use ``backward`` (parity: contrib/autograd.py:158,
    which is likewise just ``backward(outputs)``; gradients land on the
    arrays that called ``attach_grad``)."""
    backward(outputs)
