"""Automatic symbol naming: NameManager / Prefix.

Parity: ``python/mxnet/name.py`` (NameManager:25, Prefix:93).  The
reference generates canonical names for anonymous symbols
("fullyconnected0", ...) through a thread-local manager stack users can
override::

    with mx.name.Prefix("resnet_"):
        fc = mx.sym.FullyConnected(x, num_hidden=10)  # resnet_fullyconnected0

The symbol layer's auto-namer (``symbol/symbol.py _auto_name``) resolves
through ``NameManager.current()``; the default manager reproduces the
reference's per-hint counters.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_current = threading.local()
_default = None  # lazily-created PROCESS-wide fallback manager


class NameManager:
    """Per-hint counter naming (the reference's default behavior).

    Subclass and override :meth:`get` to change naming; install with a
    ``with`` block (managers nest, restoring the outer one on exit).
    """

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        """Canonical name: the user's ``name`` if given, else
        ``<hint><n>`` with a per-hint counter."""
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    @staticmethod
    def current():
        """The installed manager for this thread, else the PROCESS-wide
        default.  Scoped managers (``with`` blocks) are thread-local
        like the reference's; the fallback is shared so auto-names stay
        unique across threads (callers serialize via the symbol layer's
        name lock)."""
        mgr = getattr(_current, "value", None)
        if mgr is not None:
            return mgr
        global _default
        if _default is None:
            _default = NameManager()
        return _default

    def __enter__(self):
        self._old_manager = NameManager.current()
        _current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager is not None
        _current.value = self._old_manager
        return False


class Prefix(NameManager):
    """Attach a prefix to every auto-generated name (reference
    ``name.py:93``)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
