// Binary extension ABI (parity: include/mxnet/lib_api.h — ship operators
// as standalone .so files with zero framework linkage).
//
// A plugin exports plain C symbols; mxnet_tpu.library.load() dlopens the
// file, introspects the op table, and registers each op into the live
// registry.  Compute runs on the host through the XLA callback bridge
// (the same boundary the reference's CustomOp used for Python/C++
// callbacks); an optional backward entry point makes the op
// differentiable.
//
// Version 1 ABI (float32 tensors):
//
//   int   mx_plugin_abi_version(void);                 // must return 1
//   long  mx_plugin_num_ops(void);
//   const char* mx_plugin_op_name(long i);
//   long  mx_plugin_op_num_inputs(long i);
//   int   mx_plugin_op_has_backward(long i);
//
//   // write output shape for the given input shapes; return 0 on ok.
//   // out_shape is a caller-owned buffer of MX_PLUGIN_MAX_RANK longs;
//   // *out_ndim must be <= MX_PLUGIN_MAX_RANK (the loader rejects the
//   // op otherwise).
//   int mx_plugin_op_infer_shape(long i,
//                                const long* const* in_shapes,
//                                const int* in_ndims, long n_inputs,
//                                long* out_shape, int* out_ndim);
//
//   // forward: dense f32 buffers, row-major; return 0 on ok
//   int mx_plugin_op_forward(long i,
//                            const float* const* inputs,
//                            const long* const* in_shapes,
//                            const int* in_ndims, long n_inputs,
//                            float* output,
//                            const long* out_shape, int out_ndim);
//
//   // backward (optional): given inputs + out-grad, write in-grads
//   int mx_plugin_op_backward(long i,
//                             const float* const* inputs,
//                             const long* const* in_shapes,
//                             const int* in_ndims, long n_inputs,
//                             const float* out_grad,
//                             float* const* in_grads);
#ifndef MXNET_TPU_PLUGIN_API_H_
#define MXNET_TPU_PLUGIN_API_H_
#define MX_PLUGIN_ABI_VERSION 1
#define MX_PLUGIN_MAX_RANK 16
#endif  // MXNET_TPU_PLUGIN_API_H_
