// Native-tier self-tests (parity: tests/cpp/ gtest suites — engine,
// storage, operator runners).  A standalone binary with zero framework
// linkage: each check prints PASS/FAIL and the process exit code is the
// failure count.  Built and executed by tests/test_native.py's C++ layer
// so the C++ code is tested as C++, not only through ctypes.
//
// Build: g++ -O2 -std=c++17 native_selftest.cc recordio_native.cc
//            image_decode_native.cc -ljpeg -o selftest

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
long rio_index(const uint8_t*, long, long*, long*, long*, long);
long rio_gather(const uint8_t*, const long*, const long*, long, uint8_t*,
                long*);
long rio_pack(const uint8_t*, const long*, const long*, long, uint8_t*);
int rio_abi_version();
long img_decode_aug_batch(const uint8_t* const*, const long*, long, int,
                          int, const long*, const uint8_t*, int,
                          const float*, const float*, float*, uint8_t*,
                          int);
}

static int failures = 0;

#define CHECK_TRUE(cond, msg)                          \
  do {                                                 \
    if (cond) {                                        \
      std::printf("PASS %s\n", msg);                   \
    } else {                                           \
      std::printf("FAIL %s\n", msg);                   \
      ++failures;                                      \
    }                                                  \
  } while (0)

namespace {

void test_abi_version() {
  CHECK_TRUE(rio_abi_version() == 1, "rio_abi_version == 1");
}

void test_pack_index_gather_roundtrip() {
  // three records of different sizes
  const char* payloads[] = {"alpha", "bet", "gamma-gamma"};
  std::vector<uint8_t> flat;
  std::vector<long> offs, lens;
  for (const char* p : payloads) {
    offs.push_back(static_cast<long>(flat.size()));
    lens.push_back(static_cast<long>(std::strlen(p)));
    flat.insert(flat.end(), p, p + std::strlen(p));
  }
  std::vector<uint8_t> packed(flat.size() + 16 * 3);
  long wrote = rio_pack(flat.data(), offs.data(), lens.data(), 3,
                        packed.data());
  CHECK_TRUE(wrote > 0, "rio_pack writes");

  long o[8], l[8], f[8];
  long n = rio_index(packed.data(), wrote, o, l, f, 8);
  CHECK_TRUE(n == 3, "rio_index finds 3 records");
  bool lens_ok = n == 3;
  for (long i = 0; i < n && lens_ok; ++i) lens_ok = l[i] == lens[i];
  CHECK_TRUE(lens_ok, "rio_index lengths match");

  std::vector<uint8_t> out(flat.size());
  long out_offs[8];
  long total = rio_gather(packed.data(), o, l, n, out.data(), out_offs);
  CHECK_TRUE(total == static_cast<long>(flat.size()),
             "rio_gather total bytes");
  CHECK_TRUE(std::memcmp(out.data(), flat.data(), flat.size()) == 0,
             "rio_gather payload bytes");
}

void test_index_rejects_corrupt() {
  uint8_t junk[32];
  std::memset(junk, 0xAB, sizeof(junk));
  long o[4], l[4], f[4];
  CHECK_TRUE(rio_index(junk, sizeof(junk), o, l, f, 4) == -1,
             "rio_index flags bad magic");
}

void test_index_capacity_retry() {
  const char* payload = "x";
  long off = 0, len = 1;
  std::vector<uint8_t> packed(64);
  long wrote = rio_pack(reinterpret_cast<const uint8_t*>(payload), &off,
                        &len, 1, packed.data());
  long o[1], l[1], f[1];
  CHECK_TRUE(rio_index(packed.data(), wrote, o, l, f, 0) < 0,
             "rio_index reports capacity overflow");
}

void test_decode_rejects_garbage() {
  const uint8_t junk[] = {0xFF, 0xD8, 1, 2, 3};
  const uint8_t* bufs[] = {junk};
  long lens[] = {static_cast<long>(sizeof(junk))};
  long crops[] = {-1, -1, -1, -1};
  uint8_t flips[] = {0};
  float mean[] = {0, 0, 0}, scale[] = {1, 1, 1};
  std::vector<float> out(3 * 4 * 4);
  uint8_t ok[1] = {9};
  long n = img_decode_aug_batch(bufs, lens, 1, 4, 4, crops, flips, 0,
                                mean, scale, out.data(), ok, 2);
  CHECK_TRUE(n == 0 && ok[0] == 0, "decode flags corrupt jpeg");
}

}  // namespace

int main() {
  test_abi_version();
  test_pack_index_gather_roundtrip();
  test_index_rejects_corrupt();
  test_index_capacity_retry();
  test_decode_rejects_garbage();
  std::printf("%s (%d failures)\n", failures ? "SELFTEST FAILED"
                                             : "SELFTEST OK", failures);
  return failures;
}
