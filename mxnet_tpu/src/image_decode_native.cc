// Native JPEG decode + augment + batch assembly.
//
// The reference's image data plane is C++ (ImageRecordIOParser2 in
// src/io/iter_image_recordio_2.cc: multithreaded RecordIO chunk read +
// OpenCV JPEG decode + augment).  This is the TPU rebuild's native tier
// for the same role: a libjpeg-backed thread pool decodes a batch of
// JPEG payloads, crops/resizes/flips/normalizes each image, and writes
// the finished NCHW float32 batch into one contiguous buffer — all
// outside the Python GIL.  Python keeps orchestration (shuffle order,
// RNG for crop/flip decisions, label handling), which preserves
// reproducibility across the native and pure-Python paths.
//
// Built by mxnet_tpu/native.py with the system toolchain (g++ -ljpeg,
// plain extern "C" ABI via ctypes — no pybind11 in the image).

#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jmp;
};

void err_exit(j_common_ptr cinfo) {
  ErrMgr* e = reinterpret_cast<ErrMgr*>(cinfo->err);
  std::longjmp(e->jmp, 1);
}

// Decode one JPEG into an RGB HWC uint8 buffer (caller frees).
// Returns true on success and sets (h, w).
bool decode_rgb(const uint8_t* buf, long len, std::vector<uint8_t>* out,
                int* h, int* w) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;  // grayscale upsamples to RGB
  jpeg_start_decompress(&cinfo);
  *h = static_cast<int>(cinfo.output_height);
  *w = static_cast<int>(cinfo.output_width);
  out->resize(static_cast<size_t>(*h) * *w * 3);
  const int stride = *w * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
        static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Sample source pixel (bilinear interp=1, nearest interp=0) from an RGB
// HWC crop window and write a normalized CHW float pixel.
inline void resample_to(const uint8_t* src, int sh, int sw, int x0, int y0,
                        int cw, int ch, int out_h, int out_w, int interp,
                        bool flip, const float* mean, const float* scale,
                        float* dst) {
  const long plane = static_cast<long>(out_h) * out_w;
  for (int oy = 0; oy < out_h; ++oy) {
    // only the bilinear branch reads fy; when ch == out_h the formula
    // reduces to oy exactly, so no special case.  Coordinate math is
    // double throughout to match numpy's float64 in the python path.
    const double fy = interp ? (oy + 0.5) * ch / out_h - 0.5 : 0.0;
    for (int ox = 0; ox < out_w; ++ox) {
      const int oxx = flip ? (out_w - 1 - ox) : ox;
      float r, g, b;
      if (cw == out_w && ch == out_h) {
        const uint8_t* p = src +
            (static_cast<long>(y0 + oy) * sw + (x0 + ox)) * 3;
        r = p[0]; g = p[1]; b = p[2];
      } else if (!interp) {
        // index math in double to match numpy's float64 source-index
        // selection in the python path exactly
        int sy = y0 + static_cast<int>(oy * static_cast<double>(ch) / out_h);
        int sx = x0 + static_cast<int>(ox * static_cast<double>(cw) / out_w);
        if (sy > y0 + ch - 1) sy = y0 + ch - 1;
        if (sx > x0 + cw - 1) sx = x0 + cw - 1;
        const uint8_t* p = src + (static_cast<long>(sy) * sw + sx) * 3;
        r = p[0]; g = p[1]; b = p[2];
      } else {
        double fx = (ox + 0.5) * cw / out_w - 0.5;
        double yy = fy < 0 ? 0 : fy;
        double xx = fx < 0 ? 0 : fx;
        if (yy > ch - 1) yy = static_cast<double>(ch - 1);
        if (xx > cw - 1) xx = static_cast<double>(cw - 1);
        const int iy = static_cast<int>(yy), ix = static_cast<int>(xx);
        const int iy1 = iy + 1 > ch - 1 ? iy : iy + 1;
        const int ix1 = ix + 1 > cw - 1 ? ix : ix + 1;
        const float wy = static_cast<float>(yy - iy),
                    wx = static_cast<float>(xx - ix);
        const uint8_t* p00 = src +
            (static_cast<long>(y0 + iy) * sw + (x0 + ix)) * 3;
        const uint8_t* p01 = src +
            (static_cast<long>(y0 + iy) * sw + (x0 + ix1)) * 3;
        const uint8_t* p10 = src +
            (static_cast<long>(y0 + iy1) * sw + (x0 + ix)) * 3;
        const uint8_t* p11 = src +
            (static_cast<long>(y0 + iy1) * sw + (x0 + ix1)) * 3;
        r = (1 - wy) * ((1 - wx) * p00[0] + wx * p01[0]) +
            wy * ((1 - wx) * p10[0] + wx * p11[0]);
        g = (1 - wy) * ((1 - wx) * p00[1] + wx * p01[1]) +
            wy * ((1 - wx) * p10[1] + wx * p11[1]);
        b = (1 - wy) * ((1 - wx) * p00[2] + wx * p01[2]) +
            wy * ((1 - wx) * p10[2] + wx * p11[2]);
      }
      float* px = dst + static_cast<long>(oy) * out_w + oxx;
      px[0] = (r - mean[0]) * scale[0];
      px[plane] = (g - mean[1]) * scale[1];
      px[2 * plane] = (b - mean[2]) * scale[2];
    }
  }
}

}  // namespace

extern "C" {

// Probe JPEG dimensions without a full decode.  Returns 0 on success.
int img_jpeg_probe(const uint8_t* buf, long len, int* h, int* w) {
  jpeg_decompress_struct cinfo;
  ErrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = err_exit;
  if (setjmp(jerr.jmp)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = static_cast<int>(cinfo.image_height);
  *w = static_cast<int>(cinfo.image_width);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode + augment a batch of n JPEGs into a contiguous NCHW float32
// batch.  Per image i:
//   crop_xywh[4i..4i+3]: source crop rect; cw/ch <= 0 means full frame
//     (the Python side passes exact (out_w, out_h) windows for the
//     random/center-crop path, or full frame for the resize path),
//   flips[i]: horizontal mirror,
//   interp: 0 nearest / 1 bilinear for the resize path,
//   mean/scale: per-RGB-channel normalization out = (pix - mean)*scale.
// ok[i] gets 1/0 per image; returns the number decoded successfully.
long img_decode_aug_batch(const uint8_t* const* bufs, const long* lens,
                          long n, int out_h, int out_w,
                          const long* crop_xywh, const uint8_t* flips,
                          int interp, const float* mean,
                          const float* scale, float* out, uint8_t* ok,
                          int nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = static_cast<int>(n);
  std::vector<long> done(nthreads, 0);

  auto work = [&](int tid) {
    std::vector<uint8_t> rgb;
    for (long i = tid; i < n; i += nthreads) {
      int h = 0, w = 0;
      if (!decode_rgb(bufs[i], lens[i], &rgb, &h, &w)) {
        ok[i] = 0;
        continue;
      }
      long x0 = crop_xywh[4 * i], y0 = crop_xywh[4 * i + 1];
      long cw = crop_xywh[4 * i + 2], ch = crop_xywh[4 * i + 3];
      if (cw <= 0 || ch <= 0) { x0 = 0; y0 = 0; cw = w; ch = h; }
      if (x0 < 0) x0 = 0;
      if (y0 < 0) y0 = 0;
      if (x0 + cw > w) cw = w - x0;
      if (y0 + ch > h) ch = h - y0;
      if (cw <= 0 || ch <= 0) {
        ok[i] = 0;
        continue;
      }
      resample_to(rgb.data(), h, w, static_cast<int>(x0),
                  static_cast<int>(y0), static_cast<int>(cw),
                  static_cast<int>(ch), out_h, out_w, interp,
                  flips[i] != 0, mean, scale,
                  out + static_cast<long>(i) * 3 * out_h * out_w);
      ok[i] = 1;
      ++done[tid];
    }
  };

  std::vector<std::thread> pool;
  for (int t = 1; t < nthreads; ++t) pool.emplace_back(work, t);
  work(0);
  for (auto& th : pool) th.join();
  long total = 0;
  for (long d : done) total += d;
  return total;
}

}  // extern "C"
