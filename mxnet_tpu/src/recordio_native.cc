// Native RecordIO chunk parser + batch gather.
//
// The reference implements its data plane in C++ (dmlc-core RecordIO +
// src/io/iter_image_recordio_2.cc multithreaded parser); this is the
// TPU rebuild's native tier for the same role: scanning a RecordIO
// buffer into an (offset, length) index and gathering record batches
// into contiguous memory happen here at memcpy speed, while Python keeps
// orchestration.  Built as a plain shared library (extern "C" + ctypes —
// no pybind11 in the image) by mxnet_tpu/native.py at first use.
//
// Wire format (dmlc-core recordio; mirrored by mxnet_tpu/recordio.py):
//   [magic:u32 = 0xced7230a][lrec:u32][data][pad to 4B]
//   lrec upper 3 bits: continuation flag (0 whole, 1 begin, 2 middle,
//   3 end); lower 29 bits: data length.

#include <cstdint>
#include <cstring>

namespace {
constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLRecBits = 29;
constexpr uint32_t kLenMask = (1u << kLRecBits) - 1u;

inline uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, sizeof(v));
  return v;
}
}  // namespace

extern "C" {

// Scan `buf[0:n)` and write one entry per *physical* record part:
// data offset, data length, continuation flag.  Returns the number of
// parts found, or -cap-1 if `cap` was too small (call again bigger),
// or -1 on a corrupt stream (bad magic mid-file).
long rio_index(const uint8_t* buf, long n, long* offsets, long* lengths,
               long* flags, long cap) {
  long pos = 0;
  long count = 0;
  while (pos + 8 <= n) {
    if (read_u32(buf + pos) != kMagic) return -1;
    const uint32_t lrec = read_u32(buf + pos + 4);
    const long len = static_cast<long>(lrec & kLenMask);
    const long flag = static_cast<long>(lrec >> kLRecBits);
    if (pos + 8 + len > n) break;  // truncated tail: stop cleanly
    if (count >= cap) return -cap - 1;
    offsets[count] = pos + 8;
    lengths[count] = len;
    flags[count] = flag;
    ++count;
    long adv = len;
    if (adv % 4 != 0) adv += 4 - (adv % 4);
    pos += 8 + adv;
  }
  return count;
}

// Gather `count` records (parallel offset/length arrays) from `buf`
// into `out` back to back; writes each record's start position within
// `out` to `out_offsets`.  Returns total bytes written.
long rio_gather(const uint8_t* buf, const long* offsets,
                const long* lengths, long count, uint8_t* out,
                long* out_offsets) {
  long w = 0;
  for (long i = 0; i < count; ++i) {
    std::memcpy(out + w, buf + offsets[i], lengths[i]);
    out_offsets[i] = w;
    w += lengths[i];
  }
  return w;
}

// Pack `count` records into RecordIO framing inside `out` (caller sizes
// out >= sum(lengths) + 12*count).  Returns bytes written.
long rio_pack(const uint8_t* data, const long* offsets,
              const long* lengths, long count, uint8_t* out) {
  long w = 0;
  for (long i = 0; i < count; ++i) {
    const uint32_t magic = kMagic;
    const uint32_t lrec = static_cast<uint32_t>(lengths[i]) & kLenMask;
    std::memcpy(out + w, &magic, 4);
    std::memcpy(out + w + 4, &lrec, 4);
    std::memcpy(out + w + 8, data + offsets[i], lengths[i]);
    w += 8 + lengths[i];
    while (w % 4 != 0) out[w++] = 0;
  }
  return w;
}

int rio_abi_version() { return 1; }

}  // extern "C"
