"""Gluon: the imperative high-level API (parity: python/mxnet/gluon/)."""
from .parameter import (  # noqa: F401
    Parameter, Constant, ParameterDict, DeferredInitializationError,
)
from .block import Block, HybridBlock, SymbolBlock  # noqa: F401
from .trainer import Trainer  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import loss  # noqa: F401
from . import data  # noqa: F401
from . import model_zoo  # noqa: F401
from . import contrib  # noqa: F401
from . import utils  # noqa: F401
