"""Gluon losses (parity: python/mxnet/gluon/loss.py)."""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .block import HybridBlock


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Parity: loss.py _apply_weighting."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (int, float)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base loss (parity: loss.Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            self.__class__.__name__, self._batch_axis, self._weight)

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise NotImplementedError


def _batch_mean(F, loss, batch_axis):
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    if not axes:
        return loss
    return F.mean(loss, axis=axes)


class L2Loss(Loss):
    """0.5 * (pred - label)^2 (parity: loss.L2Loss)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Parity: loss.SigmoidBinaryCrossEntropyLoss (from_sigmoid variants)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                # max(x,0) - x*z + log(1+exp(-|x|)), numerically stable
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = F.relu(pred) - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu") +
                     F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label +
                         F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label,
                                         pos_weight) +
                         F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Parity: loss.SoftmaxCrossEntropyLoss."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """Parity: loss.KLDivLoss."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=tuple(
            i for i in range(loss.ndim) if i != self._batch_axis))


class CTCLoss(Loss):
    """Parity: loss.CTCLoss (op CTCLoss / nn/ctc_loss.cc)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)
        loss, _ = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                            use_data_lengths=pred_lengths is not None,
                            use_label_lengths=label_lengths is not None,
                            blank_label="last")
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    """Parity: loss.HuberLoss."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class HingeLoss(Loss):
    """Parity: loss.HingeLoss."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    """Parity: loss.LogisticLoss (binary/signed labels)."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(
                "label_format can only be signed or binary, got %s"
                % label_format)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class TripletLoss(Loss):
    """Parity: loss.TripletLoss."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis + 1 if pred.ndim > 1 else None)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    """Parity: loss.PoissonNLLLoss."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = target * F.log(target + epsilon) - target + \
                0.5 * F.log(2 * float(_np.pi) * (target + epsilon))
            stirling = stirling * (target > 1)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    """Parity: loss.CosineEmbeddingLoss."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos = self._cosine_similarity(F, input1, input2)
        label = label.reshape((-1, 1))
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)

    @staticmethod
    def _cosine_similarity(F, x, y, axis=-1):
        x_norm = F.norm(x, axis=axis).reshape((-1, 1))
        y_norm = F.norm(y, axis=axis).reshape((-1, 1))
        xy = F.sum(x * y, axis=axis).reshape((-1, 1))
        eps_arr = 1e-12
        return xy / F.broadcast_maximum(
            x_norm * y_norm, eps_arr * F.ones_like(x_norm))


class SDMLLoss(Loss):
    """Smoothed Deep Metric Learning loss (parity: loss.SDMLLoss,
    Bonadiman et al. 2019).  Two aligned minibatches of vectors — row i
    of ``x1`` pairs with row i of ``x2``; every other row acts as an
    in-batch negative.  The pairwise (squared-euclidean) distance matrix
    is softmaxed into similarity probabilities and pulled toward a
    label-smoothed identity matrix with KL divergence.
    """

    def __init__(self, smoothing_parameter=0.3, weight=1., batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self.kl_loss = KLDivLoss(from_logits=True)
        self.smoothing_parameter = smoothing_parameter

    @staticmethod
    def _compute_distances(F, x1, x2):
        b, d = x1.shape
        x1_ = F.broadcast_to(F.expand_dims(x1, 1), (b, b, d))
        x2_ = F.broadcast_to(F.expand_dims(x2, 0), (b, b, d))
        return F.sum(F.square(x1_ - x2_), axis=2)

    def _compute_labels(self, F, batch_size):
        gold = F.one_hot(F.arange(batch_size), batch_size)
        return gold * (1 - self.smoothing_parameter) \
            + (1 - gold) * self.smoothing_parameter / (batch_size - 1)

    def hybrid_forward(self, F, x1, x2):
        batch_size = x1.shape[0]
        labels = self._compute_labels(F, batch_size)
        distances = self._compute_distances(F, x1, x2)
        log_probabilities = F.log_softmax(-distances, axis=1)
        # scale by batch_size: KLDivLoss averages over the label axis,
        # the paper's formulation sums (reference does the same)
        return self.kl_loss(log_probabilities, labels) * batch_size
