"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` — ``Parameter`` (``:47``) with
deferred shape init (``DeferredInitializationError:43``), per-context data
replicas, and ``ParameterDict`` (``:706``).

TPU-native: a parameter owns ONE logical NDArray; multi-device placement is a
*sharding* of that array over a ``jax.sharding.Mesh`` (annotated via
``mxnet_tpu.parallel``), not per-context replicas — so ``list_data()`` has a
single entry and replication is the GSPMD compiler's job.  Deferred shape
inference is kept: layers created with unknown in-features materialize on
first forward.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray, _to_jax_dtype
from ..telemetry import memdump as _memdump
from .. import initializer as init_mod
from .. import autograd


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is known (parity: parameter.py:43)."""


class Parameter:
    """A weight/bias/aux tensor with gradient bookkeeping.

    Parity: ``gluon.Parameter`` (parameter.py:47).  ``shape`` entries of 0 mean
    unknown-until-first-forward (deferred init).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data = None          # NDArray once initialized
        self._deferred_init = None  # (init, ctx, default_init) awaiting shape
        self._sharding = None       # optional jax.sharding spec (parallel pkg)

    # ------------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        if len(self._shape) != len(new_shape) or any(
            a != b for a, b in zip(self._shape, new_shape) if a != 0
        ):
            raise MXNetError(
                "cannot reset shape of %s from %s to %s"
                % (self.name, self._shape, new_shape))
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError("invalid grad_req %r" % req)
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._marked = False
            else:
                self._data.attach_grad(req)

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Materialize data (parity: Parameter.initialize, parameter.py:360)."""
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = init_mod.Uniform()
        if ctx is not None and isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # single logical array; placement is sharding's job
        if self._shape is None or any(s == 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                "cannot initialize parameter %s of unknown shape %s; "
                "set allow_deferred_init=True or specify the full shape"
                % (self.name, self._shape))
        self._init_impl(init, ctx, default_init)

    def _init_impl(self, init, ctx, default_init):
        ctx = ctx or current_context()
        initializer = init_mod.create(init) if init is not None else (
            init_mod.create(self.init) if self.init is not None
            else init_mod.create(default_init))
        desc = init_mod.InitDesc(self.name)
        data = initializer(desc, self._shape, _to_jax_dtype(self.dtype))
        if isinstance(data, jax.Array):
            # jax-random initializers materialize on the DEFAULT backend
            # device; commit to the declared context so parameters and
            # batches agree on placement (a tpu-committed weight plus a
            # cpu-committed batch is a device-mismatch error at dispatch)
            data = jax.device_put(data, ctx.jax_device)
            _memdump.tag(data, origin="param", label=self.name)
        with _memdump.origin("param"):
            self._data = NDArray(data, ctx=ctx)
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)
        self._deferred_init = None

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if self._shape is None or any(s == 0 for s in self._shape):
            raise DeferredInitializationError(
                "parameter %s still has unknown shape %s"
                % (self.name, self._shape))
        init, ctx, default_init = self._deferred_init
        # Initializer RNG must not run under an active jax trace (hybridize's
        # shape pass) — autograd.pause keeps tape clean; numpy/jax const ok.
        with autograd.pause():
            self._init_impl(init, ctx, default_init)

    # ------------------------------------------------------------------
    # access
    # ------------------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                "parameter %s was not initialized yet: unknown shape %s"
                % (self.name, self._shape))
        raise MXNetError(
            "parameter %s has not been initialized; call .initialize() "
            "or block.initialize()" % self.name)

    def data(self, ctx=None):
        """The parameter NDArray (single logical copy; see module docstring)."""
        self._check_initialized()
        from .block import _trace_param_lookup

        traced = _trace_param_lookup(self)
        if traced is not None:
            return traced
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad_req == "null":
            raise MXNetError(
                "parameter %s has grad_req='null'" % self.name)
        g = self._data.grad
        if g is None:
            g = NDArray(jnp.zeros(self._data.shape, self._data.dtype),
                        ctx=self._data.context)
        return g

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init is not None:
                ctx = self._deferred_init[1]
                return [ctx or current_context()]
            raise MXNetError("parameter %s not initialized" % self.name)
        return [self._data.context]

    def set_data(self, data):
        """Replace the value, preserving grad bookkeeping."""
        if self._data is None:
            if self._deferred_init is not None:
                self.shape = tuple(data.shape)
                self._finish_deferred_init()
            else:
                raise MXNetError("parameter %s not initialized" % self.name)
        d = data.data() if isinstance(data, NDArray) else jnp.asarray(data)
        self._data._set_data(d.astype(self._data.dtype))

    def zero_grad(self):
        if self._data is not None:
            self._data.zero_grad()

    def reset_ctx(self, ctx):
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        if self._data is not None:
            self._data = self._data.as_in_context(ctx)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            marked = self._data._marked
            self._data = self._data.astype(dtype)
            if marked:
                self._data.attach_grad(self._grad_req)

    def var(self):
        """Symbol placeholder for this parameter (symbolic API)."""
        from ..symbol import var

        return var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self._shape, self.dtype)


class Constant(Parameter):
    """Non-differentiable constant parameter (parity: gluon.Constant)."""

    def __init__(self, name, value):
        if isinstance(value, NDArray):
            value = value.asnumpy()
        value = _np.asarray(value)
        if value.dtype == _np.float64:
            value = value.astype(_np.float32)
        elif value.dtype == _np.int64:
            value = value.astype(_np.int32)
        self.value = value
        super().__init__(
            name, grad_req="null", shape=value.shape,
            dtype=str(value.dtype),
            init=init_mod.Constant(0.0))
        # bake the value in via a closure-initializer
        outer = self

        class _ValueInit(init_mod.Initializer):
            def _init_weight(self, desc, shape, dtype):
                return jnp.asarray(outer.value, dtype)

            def __call__(self, desc, shape, dtype=jnp.float32):
                return self._init_weight(desc, shape, dtype)

        self.init = _ValueInit()


class ParameterDict:
    """Ordered name→Parameter mapping with prefix + shared-dict lookup.

    Parity: ``gluon.ParameterDict`` (parameter.py:706).
    """

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name):
        return self._params[name]

    def __repr__(self):
        body = "\n".join("  %s" % p for p in self._params.values())
        return "ParameterDict '%s' (\n%s\n)" % (self._prefix, body)

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Get or create ``prefix+name`` (parity: ParameterDict.get)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
            return param
        for k, v in kwargs.items():
            if k == "shape":
                if v is not None:
                    param.shape = tuple(
                        v if not isinstance(v, int) else (v,))
            elif k == "init":
                if v is not None and param.init is None:
                    param.init = v
            elif getattr(param, k, None) in (None,) and v is not None:
                setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(
                    "no constant %s and no value given" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter name %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for p in self._params.values():
            p.initialize(None, ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import ndarray as _ndm

        arg = {}
        for p in self._params.values():
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p.data()
        _ndm.save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import ndarray as _ndm

        loaded = _ndm.load(filename, ctx=ctx)
        if not isinstance(loaded, dict):
            raise MXNetError("parameter file %s is not a dict" % filename)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self._params.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(
                        "parameter %s missing in file %s" % (name, filename))
                continue
            arr = loaded[name]
            if p._data is None:
                p.shape = tuple(arr.shape)
                if p._deferred_init is not None:
                    p._finish_deferred_init()
                else:
                    p.initialize(ctx=ctx)
            p.set_data(arr)
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(
                    "file %s has extra parameters %s (pass ignore_extra=True)"
                    % (filename, sorted(extra)))
