"""Datasets (parity: ``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from ...base import MXNetError


class Dataset:
    """Abstract random-access dataset (parity: dataset.py Dataset)."""

    def __getitem__(self, idx):  # pragma: no cover - abstract
        raise NotImplementedError

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def filter(self, fn):
        """Return a dataset with only samples for which fn(sample) is True."""
        return _FilteredDataset(self, fn)

    def shard(self, num_shards, index):
        """Return the index-th of num_shards contiguous-strided shards —
        the per-host split used for data-parallel input pipelines."""
        return _ShardedDataset(self, num_shards, index)

    def take(self, count):
        return _TakenDataset(self, count)

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    """Wrap any list-like (parity: dataset.py SimpleDataset)."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _FilteredDataset(SimpleDataset):
    def __init__(self, dataset, fn):
        super().__init__([i for i in range(len(dataset))
                          if fn(dataset[i])])
        self._dataset = dataset

    def __getitem__(self, idx):
        return self._dataset[self._data[idx]]


class _ShardedDataset(Dataset):
    def __init__(self, dataset, num_shards, index):
        if not 0 <= index < num_shards:
            raise MXNetError("shard index %d out of range [0, %d)"
                             % (index, num_shards))
        self._dataset = dataset
        self._num = num_shards
        self._index = index
        length = len(dataset)
        self._start = (length // num_shards) * index + \
            min(index, length % num_shards)
        self._end = self._start + length // num_shards + \
            (1 if index < length % num_shards else 0)

    def __len__(self):
        return self._end - self._start

    def __getitem__(self, idx):
        return self._dataset[self._start + idx]


class _TakenDataset(Dataset):
    def __init__(self, dataset, count):
        self._dataset = dataset
        self._count = min(count, len(dataset))

    def __len__(self):
        return self._count

    def __getitem__(self, idx):
        if idx >= self._count:
            raise IndexError
        return self._dataset[idx]


class ArrayDataset(Dataset):
    """Zip several array-likes (parity: dataset.py ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0, "Needs at least 1 arrays"
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                "All arrays must have the same length; array[0] has " \
                "length %d while array[%d] has %d." % (
                    self._length, i, len(data))
            if isinstance(data, Dataset):
                self._data.append(data)
            else:
                self._data.append(SimpleDataset(data))

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Each sample is one record of a RecordIO file (dataset.py:273)."""

    def __init__(self, filename):
        from ... import recordio
        import os
        self.idx_file = os.path.splitext(filename)[0] + '.idx'
        self.filename = filename
        self._record = recordio.MXIndexedRecordIO(
            self.idx_file, self.filename, 'r')

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)
