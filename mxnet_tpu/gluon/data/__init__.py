"""Gluon data API (parity: ``python/mxnet/gluon/data/``)."""
from .dataset import (  # noqa: F401
    Dataset, SimpleDataset, ArrayDataset, RecordFileDataset,
)
from .sampler import (  # noqa: F401
    Sampler, SequentialSampler, RandomSampler, BatchSampler,
    FilterSampler,
)
from .dataloader import DataLoader  # noqa: F401
# the reference keeps its pre-1.5 loader importable under this name;
# the modern loader serves both roles here
DataLoaderV1 = DataLoader
from . import vision  # noqa: F401
