"""Gluon data API (parity: ``python/mxnet/gluon/data/``)."""
from .dataset import (  # noqa: F401
    Dataset, SimpleDataset, ArrayDataset, RecordFileDataset,
)
from .sampler import (  # noqa: F401
    Sampler, SequentialSampler, RandomSampler, BatchSampler,
)
from .dataloader import DataLoader  # noqa: F401
from . import vision  # noqa: F401
