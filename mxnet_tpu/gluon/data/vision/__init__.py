"""Vision datasets + transforms (parity: gluon/data/vision/)."""
from .datasets import (  # noqa: F401
    MNIST, FashionMNIST, CIFAR10, CIFAR100,
    ImageRecordDataset, ImageFolderDataset,
)
from . import transforms  # noqa: F401
