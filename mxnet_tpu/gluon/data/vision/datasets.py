"""Vision datasets (parity: ``python/mxnet/gluon/data/vision/datasets.py``).

No-egress environment: datasets read standard files already on disk
(idx/idx.gz for MNIST-family, pickled batches for CIFAR); there is no
download step.  Layout of returned samples matches the reference: HWC uint8
image + scalar label.
"""
from __future__ import annotations

import os
import gzip
import pickle
import struct

import numpy as np

from ....base import MXNetError
from .... import ndarray as nd
from ..dataset import Dataset, RecordFileDataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):  # pragma: no cover - abstract
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (parity: datasets.py MNIST:57)."""

    _train_files = ('train-images-idx3-ubyte', 'train-labels-idx1-ubyte')
    _test_files = ('t10k-images-idx3-ubyte', 't10k-labels-idx1-ubyte')

    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets',
                                         'mnist'),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _find(self, name):
        for cand in (os.path.join(self._root, name),
                     os.path.join(self._root, name + '.gz')):
            if os.path.exists(cand):
                return cand
        raise MXNetError(
            "%s(.gz) not found under %s (no-egress environment: place the "
            "standard idx files there)" % (name, self._root))

    def _get_data(self):
        from ....io.io import _read_idx_images, _read_idx_labels
        img_name, lbl_name = self._train_files if self._train \
            else self._test_files
        images = _read_idx_images(self._find(img_name))
        labels = _read_idx_labels(self._find(lbl_name))
        self._data = nd.array(images[..., None], dtype='uint8')
        self._label = labels.astype(np.int32)


class FashionMNIST(MNIST):
    """FashionMNIST — same idx layout, different files (datasets.py:123)."""

    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets',
                                         'fashion-mnist'),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from local python-pickle batches (datasets.py CIFAR10:153)."""

    _train_names = ['data_batch_%d' % i for i in range(1, 6)]
    _test_names = ['test_batch']
    _coarse = False

    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets',
                                         'cifar10'),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, 'rb') as f:
            batch = pickle.load(f, encoding='latin1')
        data = np.asarray(batch['data'], dtype=np.uint8)
        data = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = 'coarse_labels' if self._coarse else (
            'fine_labels' if 'fine_labels' in batch else 'labels')
        label = np.asarray(batch[key], dtype=np.int32)
        return data, label

    def _get_data(self):
        names = self._train_names if self._train else self._test_names
        found = []
        for name in names:
            for cand in (os.path.join(self._root, name),
                         os.path.join(self._root, 'cifar-10-batches-py',
                                      name),
                         os.path.join(self._root, 'cifar-100-python',
                                      name)):
                if os.path.exists(cand):
                    found.append(cand)
                    break
        if not found:
            raise MXNetError(
                "CIFAR batches %s not found under %s (no-egress "
                "environment)" % (names, self._root))
        data, label = zip(*[self._read_batch(name) for name in found])
        self._data = nd.array(np.concatenate(data), dtype='uint8')
        self._label = np.concatenate(label)


class CIFAR100(CIFAR10):
    """CIFAR100 (parity: datasets.py CIFAR100:208)."""

    _train_names = ['train']
    _test_names = ['test']

    def __init__(self, root=os.path.join('~', '.mxnet', 'datasets',
                                         'cifar100'),
                 fine_label=False, train=True, transform=None):
        self._coarse = not fine_label
        super().__init__(root, train, transform)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a RecordIO pack (datasets.py:254)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio
        record = super().__getitem__(idx)
        header, img = recordio.unpack_img(record)
        img = nd.array(img, dtype='uint8')
        if self._transform is not None:
            return self._transform(img, header.label)
        return img, header.label


class ImageFolderDataset(Dataset):
    """root/category/image.ext layout (datasets.py ImageFolderDataset:290).

    Image decode requires .npy payloads or PIL; standard image formats are
    listed for parity but decodable only when a codec is importable.
    """

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = ['.jpg', '.jpeg', '.png', '.npy']
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        path, label = self.items[idx]
        if path.endswith('.npy'):
            img = np.load(path)
        else:
            try:
                from PIL import Image
                img = np.asarray(Image.open(path))
            except ImportError:
                raise MXNetError(
                    "decoding %s needs PIL; use .npy images in this "
                    "environment" % path)
        img = nd.array(img, dtype='uint8')
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
