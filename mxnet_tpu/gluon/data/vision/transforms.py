"""Vision transforms (parity: gluon/data/vision/transforms.py).

Transforms are HybridBlocks operating on HWC images (uint8 in, float out
after ToTensor) exactly as in the reference; under a hybridized pipeline
they fuse into the surrounding XLA program.
"""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from .... import ndarray as nd


class Compose(Sequential):
    """Sequentially compose transforms (parity: transforms.py Compose:40)."""

    def __init__(self, transforms):
        super().__init__()
        transforms.append(None)
        hybrid = []
        for i in transforms:
            if isinstance(i, HybridBlock):
                hybrid.append(i)
                continue
            if len(hybrid) == 1:
                self.add(hybrid[0])
                hybrid = []
            elif len(hybrid) > 1:
                hblock = HybridSequential()
                for j in hybrid:
                    hblock.add(j)
                hblock.hybridize()
                self.add(hblock)
                hybrid = []
            if i is not None:
                self.add(i)


class Cast(HybridBlock):
    def __init__(self, dtype='float32'):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (transforms.py ToTensor:91)."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, 'float32') / 255.0
        if len(x.shape) == 3:
            return F.transpose(x, (2, 0, 1))
        return F.transpose(x, (0, 3, 1, 2))


class Normalize(HybridBlock):
    """(x - mean) / std per channel, CHW input (transforms.py:130)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, dtype=np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, dtype=np.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        mean = F.array(self._mean, dtype=str(x.dtype))
        std = F.array(self._std, dtype=str(x.dtype))
        return (x - mean) / std


def _resize_hwc(x, w, h):
    arr = x.asnumpy() if hasattr(x, 'asnumpy') else np.asarray(x)
    ih, iw = arr.shape[:2]
    yy = np.clip((np.arange(h) * ih / float(h)).astype(int), 0, ih - 1)
    xx = np.clip((np.arange(w) * iw / float(w)).astype(int), 0, iw - 1)
    return nd.array(arr[yy][:, xx], dtype=str(arr.dtype))


class Resize(Block):
    """Resize to (w, h); nearest interpolation (transforms.py Resize:303)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        if isinstance(self._size, int):
            if self._keep:
                h, w = x.shape[:2]
                if h < w:
                    size = (int(self._size * w / h), self._size)
                else:
                    size = (self._size, int(self._size * h / w))
            else:
                size = (self._size, self._size)
        else:
            size = self._size
        return _resize_hwc(x, size[0], size[1])


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        ih, iw = x.shape[:2]
        if ih < h or iw < w:
            x = _resize_hwc(x, max(w, iw), max(h, ih))
            ih, iw = x.shape[:2]
        y0, x0 = (ih - h) // 2, (iw - w) // 2
        return x[y0:y0 + h, x0:x0 + w]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3/4, 4/3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        ih, iw = x.shape[:2]
        area = ih * iw
        for _ in range(10):
            target = area * np.random.uniform(*self._scale)
            aspect = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * aspect)))
            h = int(round(np.sqrt(target / aspect)))
            if w <= iw and h <= ih:
                x0 = np.random.randint(0, iw - w + 1)
                y0 = np.random.randint(0, ih - h + 1)
                crop = x[y0:y0 + h, x0:x0 + w]
                return _resize_hwc(crop, self._size[0], self._size[1])
        return CenterCrop(self._size).forward(x)


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        arr = x.asnumpy() if hasattr(x, 'asnumpy') else np.asarray(x)
        if self._pad:
            arr = np.pad(arr, ((self._pad, self._pad),
                               (self._pad, self._pad), (0, 0)))
        w, h = self._size
        ih, iw = arr.shape[:2]
        y0 = np.random.randint(0, max(1, ih - h + 1))
        x0 = np.random.randint(0, max(1, iw - w + 1))
        return nd.array(arr[y0:y0 + h, x0:x0 + w], dtype=str(arr.dtype))


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if np.random.rand() < self._p:
            return nd.flip(x, axis=1)
        return x


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if np.random.rand() < self._p:
            return nd.flip(x, axis=0)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._b, self._b)
        return nd.clip(nd.cast(x, 'float32') * alpha, 0., 255.)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._c, self._c)
        xf = nd.cast(x, 'float32')
        gray = nd.mean(xf)
        return nd.clip(xf * alpha + gray * (1 - alpha), 0., 255.)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._s, self._s)
        xf = nd.cast(x, 'float32')
        coef = nd.array(np.array([[[0.299, 0.587, 0.114]]],
                                 dtype=np.float32))
        gray = nd.sum(xf * coef, axis=2, keepdims=True)
        return nd.clip(xf * alpha + gray * (1 - alpha), 0., 255.)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        alpha = np.random.uniform(-self._h, self._h)
        xf = nd.cast(x, 'float32').asnumpy()
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], dtype=np.float32)
        tyiq = np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], dtype=np.float32)
        ityiq = np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], dtype=np.float32)
        t = ityiq @ bt @ tyiq
        out = np.clip(xf @ t.T, 0., 255.)
        return nd.array(out)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._transforms = []
        if brightness:
            self._transforms.append(RandomBrightness(brightness))
        if contrast:
            self._transforms.append(RandomContrast(contrast))
        if saturation:
            self._transforms.append(RandomSaturation(saturation))
        if hue:
            self._transforms.append(RandomHue(hue))

    def forward(self, x):
        order = np.random.permutation(len(self._transforms))
        for i in order:
            x = self._transforms[i].forward(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (transforms.py RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], dtype=np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], dtype=np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        alpha = np.random.normal(0, self._alpha, size=(3,)).astype(
            np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd.cast(x, 'float32') + nd.array(rgb.reshape(1, 1, 3))
