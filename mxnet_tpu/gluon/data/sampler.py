"""Samplers (parity: ``python/mxnet/gluon/data/sampler.py``)."""
from __future__ import annotations

import numpy as np


class Sampler:
    """Abstract index sampler."""

    def __len__(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def __iter__(self):  # pragma: no cover - abstract
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        indices = np.arange(self._length)
        np.random.shuffle(indices)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    """Samples elements for which ``fn(sample)`` is True (parity:
    gluon/data/sampler.py:77)."""

    def __init__(self, fn, dataset):
        self._fn = fn
        self._dataset = dataset
        self._indices = [i for i, sample in enumerate(dataset)
                         if fn(sample)]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    """Group a sampler into batches; last_batch in keep/discard/rollover."""

    def __init__(self, sampler, batch_size, last_batch='keep'):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == 'keep':
                yield batch
            elif self._last_batch == 'discard':
                return
            elif self._last_batch == 'rollover':
                self._prev = batch
            else:
                raise ValueError(
                    "last_batch must be one of 'keep', 'discard', or "
                    "'rollover', but got %s" % self._last_batch)

    def __len__(self):
        if self._last_batch == 'keep':
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == 'discard':
            return len(self._sampler) // self._batch_size
        if self._last_batch == 'rollover':
            return (len(self._prev) + len(self._sampler)) // \
                self._batch_size
        raise ValueError(
            "last_batch must be one of 'keep', 'discard', or 'rollover', "
            "but got %s" % self._last_batch)
