"""DataLoader (parity: ``python/mxnet/gluon/data/dataloader.py``).

The reference forks multiprocessing workers and ships batches back through
POSIX shared memory (``dataloader.py:28-111`` ForkingPickler rebuild).  A
forked worker cannot hold PJRT device handles, so the TPU-native loader
uses the reference's *thread_pool* mode as the default worker engine
(``ThreadPool`` path, ``dataloader.py:573``): decode/augment run in host
threads (NumPy releases the GIL), batches are assembled as NumPy and the
single ``device_put`` happens on the consumer side.  ``num_workers`` keeps
its meaning (0 = synchronous); prefetch depth matches the reference default
(2 * num_workers).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from collections import deque

import numpy as np

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from .sampler import SequentialSampler, RandomSampler, BatchSampler
from .dataset import Dataset


def default_batchify_fn(data):
    """Stack samples into a batch (parity: dataloader.py:default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype)


def default_mp_batchify_fn(data):
    """Parity alias — no shared-memory path is needed with threads."""
    return default_batchify_fn(data)


class DataLoader:
    """Load batches from a Dataset (parity: dataloader.py DataLoader:441)."""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 pin_device_id=0, prefetch=None, thread_pool=True,
                 timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory  # accepted for parity; host is host
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else 'keep')
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(
            0, int(prefetch) if prefetch is not None
            else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn
        self._pool = None
        if self._num_workers > 0:
            self._pool = ThreadPoolExecutor(
                max_workers=self._num_workers,
                thread_name_prefix='dataloader')

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._pool is None:
            for batch in self._batch_sampler:
                yield self._make_batch(batch)
            return
        # pipelined: keep up to prefetch batches in flight
        it = iter(self._batch_sampler)
        inflight = deque()
        try:
            for _ in range(max(1, self._prefetch)):
                try:
                    inflight.append(
                        self._pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    break
            while inflight:
                yield inflight.popleft().result(timeout=self._timeout)
                try:
                    inflight.append(
                        self._pool.submit(self._make_batch, next(it)))
                except StopIteration:
                    pass
        finally:
            for fut in inflight:
                fut.cancel()

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
