"""Gluon Block / HybridBlock.

Reference: ``python/mxnet/gluon/block.py`` — ``Block:229``, ``HybridBlock:839``
whose ``hybridize():1043`` traces ``hybrid_forward`` with Symbol proxies into
an nnvm graph executed by ``CachedOp`` (``_build_cache:933``).

TPU-native rebuild: there is no separate symbolic tracer — the jaxpr IS the
captured graph.  ``hybridize()`` arms a cache; on a cache miss the whole
imperative forward is traced by ``jax.jit`` with (rng_key, *params, *inputs)
as arguments, producing ONE XLA executable per (input shapes/dtypes, mode)
— the direct analogue of ``CachedOp::SetForwardGraph``'s shape-keyed
executable (``src/imperative/cached_op.cc:417``), with XLA doing memory
planning (= ``MXPlanMemory``) and fusion (= pointwise fusion pass) for free.
Autograd records the executable as ONE tape node via ``jax.vjp``.  Aux state
(BatchNorm moving stats) written during the trace is routed out as extra
outputs through a trace-time side channel and assigned back after each run.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import jax

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray
from .. import ndarray as _nd_module
from .. import autograd
from .. import random as _random
from ..engine import Engine
from .parameter import (
    Parameter, ParameterDict, DeferredInitializationError,
)

# ---------------------------------------------------------------------------
# trace plumbing (hybridize)
# ---------------------------------------------------------------------------
_trace_state = threading.local()


class _functional_params:
    """Context manager: run imperative forwards with parameters
    substituted by the given arrays (the functional-trace choke point
    used by hybridize, JitTrainStep, deploy, and the pipeline stages).

    ``with _functional_params(params, arrays): net._forward_imperative(x)``
    maps ``id(param) -> NDArray(array)`` for the duration and restores
    the previous trace state on exit.
    """

    def __init__(self, params, arrays):
        from ..ndarray.ndarray import NDArray

        self._map = {id(p): NDArray(a) for p, a in zip(params, arrays)}
        self._prev = None

    def __enter__(self):
        st = _trace_st()
        self._prev = (st.param_map, st.aux_updates, st.active)
        st.param_map = self._map
        st.aux_updates = []
        st.active = True
        return st

    def __exit__(self, *exc):
        st = _trace_st()
        st.param_map, st.aux_updates, st.active = self._prev
        return False


def _trace_st():
    if not hasattr(_trace_state, "param_map"):
        _trace_state.param_map = None   # id(Parameter) -> NDArray(tracer)
        _trace_state.aux_updates = None  # list of (Parameter, jax array)
        _trace_state.active = False
    return _trace_state


def _trace_param_lookup(param):
    st = _trace_st()
    if st.param_map is None:
        return None
    return st.param_map.get(id(param))


def is_tracing():
    return _trace_st().active


def record_aux_update(param, value):
    """Write an aux parameter; inside a hybridize trace the write is deferred
    and returned from the compiled executable instead (side-channel)."""
    st = _trace_st()
    data = value.data() if isinstance(value, NDArray) else value
    if st.aux_updates is not None:
        st.aux_updates.append((param, data))
    else:
        param.set_data(data)


class _BlockScope:
    """Name manager for nested blocks (parity: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_manager_next(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_name_counter = {}
_name_lock = threading.Lock()


def _name_manager_next(hint):
    with _name_lock:
        c = _name_counter.get(hint, 0)
        _name_counter[hint] = c + 1
    return "%s%d" % (hint, c)


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------
class Block:
    """Base building block (parity: gluon.Block, block.py:229)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = OrderedDict()
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def __repr__(self):
        lines = []
        for name, child in self._children.items():
            block_repr = repr(child).replace("\n", "\n  ")
            lines.append("  (%s): %s" % (name, block_repr))
        return "%s(\n%s\n)" % (self.__class__.__name__, "\n".join(lines)) \
            if lines else "%s()" % self.__class__.__name__

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        from .utils import HookHandle

        handle = HookHandle()
        handle.attach(self._forward_hooks, hook)
        return handle

    def register_forward_pre_hook(self, hook):
        from .utils import HookHandle

        handle = HookHandle()
        handle.attach(self._forward_pre_hooks, hook)
        return handle

    def collect_params(self, select=None):
        """All params of self + descendants, optionally regex-filtered.

        Parity: Block.collect_params (block.py:378).
        """
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({
                name: value for name, value in self.params.items()
                if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for param in self.params.values():
            param.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def zero_grad(self):
        self.collect_params().zero_grad()

    # -- checkpointing ---------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        """Parity: Block.save_parameters (block.py:417); block-local names.

        The write is atomic (``nd.save`` goes through ``base.atomic_path``):
        an interrupted save leaves any previous file loadable.
        """
        params = self._collect_params_with_prefix()
        from ..ndarray import ndarray as _ndm

        _ndm.save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        from ..ndarray import ndarray as _ndm

        loaded = _ndm.load(filename, ctx=ctx)
        if not isinstance(loaded, dict):
            raise MXNetError("%s is not a parameter dict file" % filename)
        if any(k.startswith(("arg:", "aux:")) for k in loaded):
            # exported-model format (HybridBlock.export / save_checkpoint)
            loaded = {k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                      else k: v for k, v in loaded.items()}
        params = self._collect_params_with_prefix()
        if loaded and not set(loaded) & set(params):
            # exported files use FLAT ParameterDict names (p.name), not
            # the structural dotted names save_parameters writes; fall
            # back to name-based matching (reference load_parameters does
            # the same when keys don't look structural)
            by_flat = {p.name: p for p in self.collect_params().values()}
            if set(loaded) & set(by_flat):
                params = by_flat
            else:
                # a FRESH net instance carries a different auto-prefix
                # (resnetv10_ vs resnetv11_); retry with the instance
                # prefix (first '_' token) stripped from both sides, but
                # only when the mapping stays unambiguous
                def strip(k):
                    return k.split("_", 1)[1] if "_" in k else k

                flat2 = {}
                for p in self.collect_params().values():
                    flat2.setdefault(strip(p.name), p)
                loaded2 = {}
                for k, v in loaded.items():
                    loaded2.setdefault(strip(k), v)
                if len(flat2) == len(by_flat) and \
                        len(loaded2) == len(loaded) and \
                        set(loaded2) & set(flat2):
                    params, loaded = flat2, loaded2
        for name, p in params.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(
                        "parameter %s missing in %s" % (name, filename))
                continue
            arr = loaded[name]
            if p._data is None:
                p.shape = tuple(arr.shape)
                if p._deferred_init is not None:
                    p._finish_deferred_init()
                else:
                    p.initialize(ctx=ctx)
            p.set_data(arr)
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(
                    "%s has extra parameters %s" % (filename, sorted(extra)))

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + name: p for name, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # -- forward ---------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):  # pragma: no cover - abstract
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary by running a forward with hooks."""
        summary_rows = []

        def make_hook(name):
            def hook(block, ins, outs):
                out = outs[0] if isinstance(outs, (list, tuple)) else outs
                n_params = sum(
                    int(p.data().size) for p in block._reg_params.values()
                    if p._data is not None)
                summary_rows.append((name or "(root)",
                                     block.__class__.__name__,
                                     tuple(out.shape), n_params))
            return hook

        handles = []
        for name, child in self._iter_blocks():
            child._forward_hooks[("__summary__", name)] = make_hook(name)
            handles.append(child)
        try:
            self(*inputs)
        finally:
            for child in handles:
                child._forward_hooks = OrderedDict(
                    (k, v) for k, v in child._forward_hooks.items()
                    if not (isinstance(k, tuple) and k[0] == "__summary__"))
        header = "%-30s %-20s %-20s %10s" % ("Layer", "Type", "Output Shape",
                                             "Params")
        lines = [header, "-" * len(header)]
        total = 0
        for name, typ, shape, n in summary_rows:
            lines.append("%-30s %-20s %-20s %10d" % (name, typ, shape, n))
            total += n
        lines.append("-" * len(header))
        lines.append("Total params: %d" % total)
        print("\n".join(lines))

    def _iter_blocks(self, prefix=""):
        yield prefix, self
        for name, child in self._children.items():
            yield from child._iter_blocks(prefix + ("." if prefix else "")
                                          + name)


# ---------------------------------------------------------------------------
# HybridBlock
# ---------------------------------------------------------------------------
class HybridBlock(Block):
    """Block that can be compiled to one XLA executable (see module doc).

    Subclasses implement ``hybrid_forward(F, x, *args, **params)`` exactly as
    in the reference; ``F`` is always the ``mxnet_tpu.ndarray`` module here
    because tracing happens at the XLA level, not the symbol level.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_ops = {}      # (shapes,dtypes,mode) -> compiled record
        self._warmed_up = False
        self._flags = {}
        self._aot_path = None      # hybridize(aot=...) bundle file
        self._aot_ops = {}         # (shapes,dtypes,mode) -> AOT record
        self._aot_entries = None   # raw bundle entries (lazy load)

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  lint=False, aot=None, **kwargs):
        """Arm/disarm compilation (parity: HybridBlock.hybridize:1043).

        ``static_alloc``/``static_shape`` accepted for API parity; XLA's
        buffer assignment always behaves like static_alloc=True.

        ``lint=True`` runs the mxlint tracing-safety pass (TS1xx,
        ``mxnet_tpu.analysis``) over this block's ``hybrid_forward`` source
        — and every child's — before arming, and raises ``MXNetError`` on
        findings: the static analogue of tracing the block and hitting a
        ConcretizationError three epochs in.

        ``aot=path`` arms warm-start serialization (compile_cache.py): each
        input signature this block compiles for is AOT-exported to ``path``
        (PJRT executable serialization), and a fresh process that
        hybridizes with the same ``aot=path`` loads the executable instead
        of tracing+compiling — bitwise-identical outputs, zero compiles.
        AOT entries serve inference; calls under ``autograd.record()`` fall
        back to the live jit path (a deserialized executable cannot be
        re-linearized for vjp).  Parameters must be initialized (e.g. via
        ``load_parameters``) before an AOT entry can serve.
        """
        if active and lint:
            findings = self.lint()
            if findings:
                raise MXNetError(
                    "hybridize(lint=True): tracing-safety findings in "
                    "hybrid_forward:\n  "
                    + "\n  ".join(str(f) for f in findings))
        self._active = active
        self._aot_path = aot if active else None
        if not active or aot is None:
            self._aot_ops = {}
            self._aot_entries = None
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        if not active:
            self._cached_ops = {}
            self._warmed_up = False
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def lint(self):
        """Run the mxlint tracing-safety pass over this block tree's
        ``hybrid_forward`` sources; returns a list of findings (empty when
        trace-safe).  See ``docs/static_analysis.md``."""
        from ..analysis import lint_block
        return lint_block(self)

    def clear_cache(self):
        self._cached_ops = {}
        self._aot_ops = {}
        self._aot_entries = None
        self._warmed_up = False

    def cast(self, dtype):
        self.clear_cache()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes given example inputs.

        Built-in layers override ``_shape_hint``; composite blocks recurse by
        simply running a forward (each layer resolves itself en route).
        """
        self._shape_hint(*args)

    def _shape_hint(self, *args):
        return None

    # -- forward dispatch -------------------------------------------------
    def forward(self, x, *args):
        from ..symbol.symbol import Symbol

        if isinstance(x, Symbol):
            return self._forward_symbolic(x, *args)
        if not isinstance(x, NDArray):
            raise MXNetError(
                "HybridBlock.forward expects NDArray inputs, got %s"
                % type(x).__name__)
        self._export_input_sig = [
            (tuple(a.shape), str(a.dtype))
            for a in (x,) + args if isinstance(a, NDArray)]
        if self._active and not is_tracing():
            return self._call_cached(x, *args)
        return self._forward_imperative(x, *args)

    def _forward_symbolic(self, x, *args):
        """Trace hybrid_forward into a Symbol graph (reference parity:
        HybridBlock's symbolic path, block.py:1090 __call__ with Symbol).

        Parameters surface as symbol variables named by their full
        ``collect_params`` key, carrying ``shape=``/``dtype=`` so
        downstream ``.shape`` reads and shape inference work.  Used by
        ONNX export and ``HybridBlock.export``.
        """
        from .. import symbol as _sym_module
        from ..symbol.symbol import var as _sym_var

        params = {}
        for name, p in self._reg_params.items():
            shape = tuple(p.shape) if p.shape else None
            if shape is not None and any(d == 0 for d in shape):
                shape = None  # deferred — the op shape-hints resolve it
            params[name] = _sym_var(
                p.name, shape=shape,
                dtype=str(p.dtype) if getattr(p, "dtype", None) else None)
        return self.hybrid_forward(_sym_module, x, *args, **params)

    def _forward_imperative(self, x, *args):
        self._shape_hint(x, *args)
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._shape_hint(x, *args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            params = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(_nd_module, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **params):  # pragma: no cover
        raise NotImplementedError

    # -- cached (compiled) path ------------------------------------------
    def _call_cached(self, *inputs):
        key = (tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
               autograd.is_training())
        if self._aot_path is not None and not autograd.is_recording():
            # warm start: serve a deserialized executable — no warmup
            # forward, no trace, no compile.  Recording falls through to
            # the live jit path (a loaded executable has no vjp).
            rec = self._aot_ops.get(key)
            if rec is None:
                rec = self._try_aot_load(key)
                if rec is not None:
                    self._aot_ops[key] = rec
            if rec is not None:
                return self._run_cached(rec, inputs)
        if not self._warmed_up:
            # First call after hybridize(): run imperatively — this resolves
            # deferred parameter shapes (CachedOp's _deferred_infer_shape) and
            # gives the answer for free; compile on the next call.
            self._warmed_up = True
            return self._forward_imperative(*inputs)
        rec = self._cached_ops.get(key)
        if rec is None:
            rec = self._build_cache(inputs)
            self._cached_ops[key] = rec
            if self._aot_path is not None:
                self._aot_export(key, rec, inputs)
                aot_rec = self._aot_ops.get(key)
                if aot_rec is not None and not autograd.is_recording():
                    # run the executable we just compiled for export rather
                    # than paying jit's own compile of the same program
                    rec = aot_rec
        return self._run_cached(rec, inputs)

    def _build_cache(self, inputs):
        """Trace the full imperative forward into one jitted executable."""
        params = list(self.collect_params().values())
        for p in params:
            p._check_initialized()
        n_params = len(params)
        outer = self
        meta = {}  # filled at trace time: n_outputs, aux param order

        def fn(rng_key, *arrays):
            st = _trace_st()
            prev = (st.param_map, st.aux_updates, st.active)
            st.param_map = {
                id(p): NDArray(a) for p, a in zip(params, arrays[:n_params])
            }
            st.aux_updates = []
            st.active = True
            try:
                with _random.trace_key_scope(rng_key):
                    nd_in = [NDArray(a) for a in arrays[n_params:]]
                    out = outer._forward_imperative(*nd_in)
                outs = [out] if isinstance(out, NDArray) else list(out)
                meta["n_outputs"] = len(outs)
                meta["aux_params"] = [p for p, _ in st.aux_updates]
                flat = [o.data() for o in outs] + [v for _, v in
                                                   st.aux_updates]
                return tuple(flat)
            finally:
                st.param_map, st.aux_updates, st.active = prev

        jitted = jax.jit(fn)
        try:
            jitted._mx_stable = True  # cacheable backward (lazy tape)
        except Exception:
            pass
        return {"fn": jitted, "params": params, "meta": meta}

    # -- AOT warm start (hybridize(aot=path), see compile_cache.py) -------
    def _bundle_entries(self):
        import os
        import warnings

        from .. import compile_cache as _ccache

        if self._aot_entries is None:
            self._aot_entries = {}
            if self._aot_path and os.path.exists(self._aot_path):
                try:
                    doc = _ccache.load_bundle(self._aot_path)
                    self._aot_entries = dict(doc["entries"])
                except MXNetError as e:
                    warnings.warn(
                        "hybridize(aot=%r): ignoring unusable bundle (%s); "
                        "falling back to live compilation"
                        % (self._aot_path, e))
        return self._aot_entries

    def _try_aot_load(self, key):
        import warnings

        from .. import compile_cache as _ccache

        entry = self._bundle_entries().get(repr(key))
        if entry is None:
            return None
        params = list(self.collect_params().values())
        try:
            for p in params:
                p._check_initialized()
        except Exception:
            return None  # deferred params: warm up imperatively first
        names = [p.name for p in params]
        if names != entry["param_names"]:
            raise MXNetError(
                "hybridize(aot=%r): bundle entry was exported with "
                "parameters %s but this block has %s — the architecture "
                "changed since export" % (self._aot_path,
                                          entry["param_names"], names))
        try:
            compiled = _ccache.deserialize_compiled(entry["blob"])
        except MXNetError as e:
            warnings.warn("hybridize(aot=%r): %s; falling back to live "
                          "compilation" % (self._aot_path, e))
            return None
        pmap = {p.name: p for p in params}
        aux = [pmap[n] for n in entry["aux_names"]]
        return {"fn": compiled, "params": params,
                "meta": {"n_outputs": entry["n_outputs"],
                         "aux_params": aux},
                "aot": True}

    def _aot_export(self, key, rec, inputs):
        import warnings

        from .. import compile_cache as _ccache

        params = rec["params"]
        datas = (
            (_random.next_key(),)
            + tuple(p.data().data() for p in params)
            + tuple(x.data() for x in inputs)
        )
        try:
            # lower() traces fn, filling rec["meta"] exactly as a call would
            compiled = rec["fn"].lower(*datas).compile()
            blob = _ccache.serialize_compiled(compiled)
        except Exception as e:
            warnings.warn(
                "hybridize(aot=%r): executable export failed (%s: %s); the "
                "block still runs, but a fresh process will recompile"
                % (self._aot_path, type(e).__name__, e))
            return
        meta = rec["meta"]
        entries = self._bundle_entries()
        entries[repr(key)] = {
            "blob": blob,
            "n_outputs": meta["n_outputs"],
            "aux_names": [p.name for p in meta["aux_params"]],
            "param_names": [p.name for p in params],
        }
        try:
            _ccache.save_bundle(self._aot_path, entries,
                                meta={"block": self.name})
        except Exception as e:
            warnings.warn("hybridize(aot=%r): bundle write failed (%s: %s)"
                          % (self._aot_path, type(e).__name__, e))
            return
        # serve subsequent non-recording calls straight from the compiled
        # executable — the exporting process pays exactly one compile
        self._aot_ops[key] = {"fn": compiled, "params": params,
                              "meta": {"n_outputs": meta["n_outputs"],
                                       "aux_params": list(
                                           meta["aux_params"])},
                              "aot": True}

    def _run_cached(self, rec, inputs):
        params = rec["params"]
        datas = (
            (_random.next_key(),)
            + tuple(p.data().data() for p in params)
            + tuple(x.data() for x in inputs)
        )
        eng = Engine.get()
        fn = rec["fn"]
        recording = autograd.is_recording()
        node = None
        flat = eng.push(lambda: fn(*datas), op_name=self.name + "_cached")
        if recording:
            # lazy tape: forward runs its cached executable; backward
            # re-linearizes through ONE cached jitted vjp per cache entry
            # (autograd._node_backward) instead of tracing jax.vjp on
            # every recorded call
            tape_inputs = [p.data() for p in params] + list(inputs)
            node = autograd.TapeNode(
                None, tape_inputs,
                [(o.shape, o.dtype) for o in flat],
                skip_grad_inputs=1,
                op_name=self.name + "_cached",
                prim=(fn, datas, 1))
        meta = rec["meta"]
        n_out = meta["n_outputs"]
        ctx = inputs[0].context if inputs else current_context()
        outs = []
        for i in range(n_out):
            arr = NDArray(flat[i], ctx=ctx)
            if node is not None:
                arr._tape_node = node
                arr._tape_index = i
            outs.append(arr)
        # write back aux updates (moving stats); not taped
        for p, new in zip(meta["aux_params"], flat[n_out:]):
            p.set_data(new)
        return outs[0] if n_out == 1 else tuple(outs)

    # -- export -----------------------------------------------------------
    def export(self, path, epoch=0):
        """Serialize to ``path-symbol.json`` + ``path-%04d.params``
        (parity: HybridBlock.export:1081).

        Like the reference, the block must have run at least one forward
        (that recorded the input signature).  The symbol file is a REAL
        Symbol graph traced via the symbolic path — loadable with
        ``SymbolBlock.imports`` / ``mx.mod.Module`` — with a structural
        JSON fallback when the graph cannot be expressed symbolically
        (e.g. data-dependent ops).
        """
        import json as _json

        from .. import symbol as _sym_mod
        from ..ndarray import ndarray as _ndm
        from ..symbol.symbol import Symbol

        params = self.collect_params()
        sym = None
        sig = getattr(self, "_export_input_sig", None)
        if sig:
            try:
                data_vars = [
                    _sym_mod.var("data" if i == 0 else "data%d" % i,
                                 shape=shp, dtype=dt)
                    for i, (shp, dt) in enumerate(sig)]
                with autograd.predict_mode():
                    out = self.forward(*data_vars)
                if isinstance(out, (list, tuple)) and all(
                        isinstance(o, Symbol) for o in out):
                    out = Symbol.Group(out)
                sym = out if isinstance(out, Symbol) else None
            except Exception:
                import logging

                # genuinely untraceable graphs (data-dependent ops) fall
                # back to the structural stub; log so tracer REGRESSIONS
                # stay visible rather than silently degrading exports
                logging.getLogger(__name__).warning(
                    "HybridBlock.export: symbolic trace failed, writing "
                    "structural stub", exc_info=True)
                sym = None
        aux_names = set(sym.list_auxiliary_states()) if sym else set()
        arg = {}
        for name, p in params.items():
            if p._data is not None:
                tag = "aux:" if name in aux_names else "arg:"
                arg[tag + name] = p.data()
        _ndm.save("%s-%04d.params" % (path, epoch), arg)
        if sym is not None:
            with open(path + "-symbol.json", "w") as f:
                f.write(sym.tojson())
            return
        desc = {"framework": "mxnet_tpu", "block": self.__class__.__name__,
                "name": self.name,
                "params": {k: list(p.shape or ()) for k, p in params.items()}}
        with open(path + "-symbol.json", "w") as f:
            _json.dump(desc, f, indent=2)


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol graph (parity: block.py:1194)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol.symbol import Symbol

        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if not isinstance(outputs, Symbol):
            raise MXNetError("SymbolBlock outputs must be a Symbol")
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._sym_outputs = outputs
        self._sym_inputs = [i.name for i in inputs]
        input_set = set(self._sym_inputs)
        for name in outputs.list_arguments():
            if name not in input_set:
                self._reg_params[name] = self.params.get(
                    name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            self._reg_params[name] = self.params.get(
                name, grad_req="null", allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load

        sym = sym_load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        from ..symbol import var

        inputs = [var(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.load_parameters(param_file, ctx=ctx,
                                allow_missing=False, ignore_extra=True)
        return ret

    def forward(self, *args):
        bindings = dict(zip(self._sym_inputs, args))
        for name, p in self._reg_params.items():
            if p._data is None and p.shape is not None and \
                    all(s != 0 for s in p.shape):
                p.initialize()
            if p._data is not None:
                bindings[name] = p.data()
        out = self._sym_outputs.eval_imperative(bindings)
        return out[0] if len(out) == 1 else out

    def hybrid_forward(self, F, *args, **kwargs):  # pragma: no cover
        raise NotImplementedError
