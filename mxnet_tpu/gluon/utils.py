"""Parallelization and misc utilities for Gluon.

Parity: ``python/mxnet/gluon/utils.py`` (``split_data:42``,
``split_and_load:88``, ``clip_global_norm:118``, ``check_sha1:172``,
``download:254``, ``HookHandle:378``).

TPU-native notes:

* ``split_and_load`` accepts either a list of :class:`~mxnet_tpu.Context`
  (reference semantics: a python list of per-device slices) **or** a
  ``jax.sharding.Mesh`` — the GSPMD form — in which case the batch is laid
  out as ONE globally-sharded array over the mesh's leading (data) axis and
  XLA handles the per-chip placement.  On TPU pods the mesh form is the one
  you want: there is no host round-trip per shard and collectives ride ICI.
* ``clip_global_norm`` runs as ONE fused jitted executable over the whole
  array list — a single kernel computes every partial norm, the global norm
  and every rescaled output, instead of the reference's per-array
  ``ndarray.dot`` dispatches (``gluon/utils.py:133-141``).
"""
from __future__ import annotations

import collections
import hashlib
import itertools
import os
import uuid
import warnings
import weakref

import numpy as _np

import jax
import jax.numpy as jnp

from .. import ndarray
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split ``data`` into ``num_slice`` slices along ``batch_axis``.

    Returns a list even when ``num_slice == 1``.  With ``even_split`` the
    batch must divide exactly; otherwise leading slices get one extra row
    (reference ``gluon/utils.py:42``).
    """
    if not isinstance(data, NDArray):
        data = ndarray.array(data)
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data."
            % (str(data.shape), num_slice, batch_axis, num_slice))

    n_each, extras = divmod(size, num_slice)
    section_sizes = (extras * [n_each + 1] + (num_slice - extras) * [n_each])
    div_points = _np.cumsum([0] + section_sizes)
    raw = data.data()
    slices = []
    for i in range(num_slice):
        idx = [slice(None)] * raw.ndim
        idx[batch_axis] = slice(int(div_points[i]), int(div_points[i + 1]))
        slices.append(NDArray(raw[tuple(idx)], ctx=data.context))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split ``data`` along ``batch_axis`` and load slices onto devices.

    Parameters
    ----------
    data : NDArray or array-like
    ctx_list : list of Context, or jax.sharding.Mesh
        A list of contexts gives the reference behaviour — a python list of
        per-context slices.  A ``Mesh`` gives the TPU-native behaviour: the
        return value is a single NDArray sharded over the mesh's first axis
        (GSPMD data parallelism); XLA moves the shards, not the host.
    batch_axis : int
    even_split : bool

    Returns
    -------
    list of NDArray (ctx list form) or NDArray (mesh form)
    """
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    if isinstance(ctx_list, Mesh):
        mesh = ctx_list
        if not isinstance(data, NDArray):
            data = ndarray.array(data)
        axis = mesh.axis_names[0]
        spec = [None] * data.ndim
        spec[batch_axis] = axis
        if even_split and data.shape[batch_axis] % mesh.shape[axis] != 0:
            raise ValueError(
                "batch %d not divisible by mesh axis %r size %d"
                % (data.shape[batch_axis], axis, mesh.shape[axis]))
        sharding = NamedSharding(mesh, PartitionSpec(*spec))
        return NDArray(jax.device_put(data.data(), sharding), ctx=data.context)

    if not isinstance(data, NDArray):
        data = ndarray.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def global_norm_scale(raws, max_norm):
    """Pure fn: global-norm clip over a list of raw jax arrays.

    Returns ``(scaled_arrays, total_norm)``.  The single shared definition
    of the clip math — used here (jitted, below) and fused into
    ``parallel.JitTrainStep``'s step executable.
    """
    total = jnp.zeros((), jnp.float32)
    for r in raws:
        total = total + jnp.sum(jnp.square(r.astype(jnp.float32)))
    total_norm = jnp.sqrt(total)
    scale = jnp.minimum(max_norm / (total_norm + 1e-8), 1.0)
    return [(r * scale.astype(r.dtype)) for r in raws], total_norm


# One executable per (tree-structure, shapes/dtypes) — all partial norms, the
# global norm and every rescaled output in a single fused XLA program.
_clip_global_norm_impl = jax.jit(global_norm_scale)


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale ``arrays`` so the joint L2 norm is at most ``max_norm``.

    In-place on each NDArray (functional swap under the hood).  Returns the
    pre-clip total norm: a float when ``check_isfinite`` (blocking), else a
    shape-(1,) NDArray (reference ``gluon/utils.py:118``).
    """
    assert len(arrays) > 0
    if not all(isinstance(a, NDArray) for a in arrays):
        raise TypeError("clip_global_norm expects a list of NDArray "
                        "(mutated in place); for raw jax arrays use "
                        "gluon.utils.global_norm_scale")
    raws = [a.data() for a in arrays]
    scaled, total_norm = _clip_global_norm_impl(
        raws, jnp.float32(max_norm))
    if check_isfinite:
        tn = float(total_norm)
        if not _np.isfinite(tn):
            warnings.warn(
                UserWarning("nan or inf is detected. "
                            "Clipping results will be undefined."),
                stacklevel=2)
    for arr, new in zip(arrays, scaled):
        arr._set_data(new)
    if check_isfinite:
        return tn
    return NDArray(total_norm.reshape((1,)))


def check_sha1(filename, sha1_hash):
    """True iff the sha1 of ``filename``'s content equals ``sha1_hash``."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def _replace_atomic(src, dst):
    try:
        os.replace(src, dst)
    except OSError:
        try:
            os.remove(src)
        except OSError:
            pass
        raise OSError("Moving downloaded temp file - {}, to {} failed. "
                      "Please retry the download.".format(src, dst))


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Download ``url`` with retries, sha1 verification and atomic rename.

    Reference ``gluon/utils.py:254``.  Uses ``requests`` when available,
    falling back to ``urllib`` (this build has no hard dependency on
    requests).
    """
    if path is None:
        fname = url.split("/")[-1]
        assert fname, ("Can't construct file-name from this URL. "
                       "Please set the `path` option manually.")
    else:
        path = os.path.expanduser(path)
        if os.path.isdir(path):
            fname = os.path.join(path, url.split("/")[-1])
        else:
            fname = path
    assert retries >= 0, \
        "Number of retries should be at least 0, currently it's {}".format(
            retries)

    if not verify_ssl:
        warnings.warn(
            "Unverified HTTPS request is being made (verify_ssl=False). "
            "Adding certificate verification is strongly advised.")

    if overwrite or not os.path.exists(fname) or (
            sha1_hash and not check_sha1(fname, sha1_hash)):
        dirname = os.path.dirname(os.path.abspath(os.path.expanduser(fname)))
        if not os.path.exists(dirname):
            os.makedirs(dirname)
        while retries + 1 > 0:
            try:
                print("Downloading {} from {}...".format(fname, url))
                tmp = "{}.{}".format(fname, str(uuid.uuid4()))
                _fetch_url(url, tmp, verify_ssl)
                if not os.path.exists(fname) or (
                        sha1_hash and not check_sha1(fname, sha1_hash)):
                    _replace_atomic(tmp, fname)
                else:
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
                    warnings.warn("File {} exists in file system so the "
                                  "downloaded file is deleted".format(fname))
                if sha1_hash and not check_sha1(fname, sha1_hash):
                    raise UserWarning(
                        "File {} is downloaded but the content hash does not "
                        "match.".format(fname))
                break
            except Exception as e:
                retries -= 1
                if retries <= 0:
                    raise e
                print("download failed due to {}, retrying, {} attempt{} left"
                      .format(repr(e), retries, "s" if retries > 1 else ""))
    return fname


def _fetch_url(url, dest, verify_ssl=True):
    """Stream ``url`` to ``dest``; file:// URLs are served locally (tests)."""
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[len("file://"):], dest)
        return
    try:
        import requests
        r = requests.get(url, stream=True, verify=verify_ssl)
        if r.status_code != 200:
            raise RuntimeError("Failed downloading url {}".format(url))
        with open(dest, "wb") as f:
            for chunk in r.iter_content(chunk_size=1048576):
                if chunk:
                    f.write(chunk)
    except ImportError:  # pragma: no cover - requests is baked into the image
        import ssl
        import urllib.request
        ctx = None if verify_ssl else ssl._create_unverified_context()
        with urllib.request.urlopen(url, context=ctx) as r, \
                open(dest, "wb") as f:
            while True:
                chunk = r.read(1048576)
                if not chunk:
                    break
                f.write(chunk)


def _get_repo_url():
    """Base URL for the Gluon model/dataset repository (reference
    ``gluon/utils.py:347``); ``MXNET_GLUON_REPO`` overrides — including
    ``file://`` trees for air-gapped deployments."""
    default_repo = "https://apache-mxnet.s3-accelerate.dualstack." \
                   "amazonaws.com/"
    repo_url = os.environ.get("MXNET_GLUON_REPO", default_repo)
    if repo_url[-1] != "/":
        repo_url = repo_url + "/"
    return repo_url


def _get_repo_file_url(namespace, filename):
    """URL of a hosted file (reference ``gluon/utils.py:355``)."""
    return "{base_url}{namespace}/{filename}".format(
        base_url=_get_repo_url(), namespace=namespace, filename=filename)


class HookHandle:
    """A removable handle for a registered hook (reference ``utils.py:378``)."""

    _next_id = itertools.count()

    def __init__(self):
        self._hooks_dict_ref = None
        self._id = None

    def attach(self, hooks_dict, hook):
        assert not self._hooks_dict_ref, \
            "The same handle cannot be attached twice."
        # monotonic key: id(self)/id(hook) can be reused after GC and would
        # silently replace a still-registered hook
        self._id = next(HookHandle._next_id)
        hooks_dict[self._id] = hook
        self._hooks_dict_ref = weakref.ref(hooks_dict)

    def detach(self):
        hooks_dict = self._hooks_dict_ref()
        if hooks_dict is not None and self._id in hooks_dict:
            del hooks_dict[self._id]

    def __getstate__(self):
        return (self._hooks_dict_ref(), self._id)

    def __setstate__(self, state):
        if state[0] is None:
            self._hooks_dict_ref = weakref.ref(collections.OrderedDict())
        else:
            self._hooks_dict_ref = weakref.ref(state[0])
        self._id = state[1]

    def __enter__(self):
        return self

    def __exit__(self, ptype, value, trace):
        self.detach()


def shape_is_known(shape):
    """Whether ``shape`` is fully known (no 0/-1/None unknown dims)."""
    if shape is None:
        return False
    unknown = (0, -1, None)
    if len(shape) == 0:
        return True
    return all(dim not in unknown for dim in shape)
