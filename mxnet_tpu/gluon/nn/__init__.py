"""Gluon neural-network layers (parity: python/mxnet/gluon/nn/)."""
from .activations import (  # noqa: F401
    Activation, LeakyReLU, PReLU, ELU, SELU, GELU, Swish,
)
from .basic_layers import (  # noqa: F401
    Sequential, HybridSequential, Dense, Dropout, BatchNorm, SyncBatchNorm,
    LayerNorm, GroupNorm, InstanceNorm, Embedding, Flatten, Identity, Lambda,
    HybridLambda, Concurrent, HybridConcurrent,
)
from .conv_layers import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    GlobalMaxPool1D, GlobalMaxPool2D, GlobalMaxPool3D,
    GlobalAvgPool1D, GlobalAvgPool2D, GlobalAvgPool3D, ReflectionPad2D,
)
