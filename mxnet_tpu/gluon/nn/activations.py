"""Gluon activation layers (parity: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock


class Activation(HybridBlock):
    """Parity: nn.Activation — act_type in relu/sigmoid/tanh/softrelu/softsign."""

    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class LeakyReLU(HybridBlock):
    """Parity: nn.LeakyReLU(alpha)."""

    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "LeakyReLU(%s)" % self._alpha


class PReLU(HybridBlock):
    """Parity: nn.PReLU — learnable slope."""

    def __init__(self, alpha_initializer=None, in_channels=1, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer as _init

        if alpha_initializer is None:
            alpha_initializer = _init.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,), init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    """Parity: nn.ELU(alpha)."""

    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Parity: nn.SELU."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    """Parity: nn.GELU."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    """Parity: nn.Swish(beta) — x * sigmoid(beta*x)."""

    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
