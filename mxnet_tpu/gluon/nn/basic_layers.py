"""Gluon basic neural-network layers.

Reference: ``python/mxnet/gluon/nn/basic_layers.py`` (Dense, Dropout,
BatchNorm, Embedding, ...) — same API; compute goes through the op registry
onto XLA (each op is a jitted XLA computation; under ``hybridize()`` the
whole net fuses into one executable).
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import Block, HybridBlock, record_aux_update
from .activations import Activation


class Sequential(Block):
    """Stack of blocks run in order (parity: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())

class HybridSequential(HybridBlock):
    """Stack compiled as one executable when hybridized."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (parity: nn.Dense).

    Weight layout (units, in_units) matches the reference FullyConnected op
    (``src/operator/nn/fully_connected.cc:258``); in_units=0 defers shape to
    first forward.
    """

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            self.act = Activation(activation, prefix=activation + "_") \
                if activation is not None else None

    def _shape_hint(self, x, *args):
        if self.weight.shape and self.weight.shape[1] == 0:
            in_units = int(_np.prod(x.shape[1:])) if self._flatten \
                else x.shape[-1]
            self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten,
                               no_bias=not self._use_bias)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "Dense(%s -> %s, %s)" % (
            shape[1] if shape and len(shape) > 1 else None, shape[0] if shape else None,
            "linear" if self.act is None else self.act)


class Dropout(HybridBlock):
    """Parity: nn.Dropout — active only in train mode (autograd.record)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        if self._rate == 0:
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return "Dropout(p = %s, axes=%s)" % (self._rate, self._axes)


class BatchNorm(HybridBlock):
    """Parity: nn.BatchNorm — moving stats updated each training forward.

    The XLA BatchNorm op returns (out, new_mean, new_var); aux writes route
    through ``record_aux_update`` so they work both imperatively and inside a
    compiled (hybridized) executable.
    """

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if axis is None:
            # reference default is axis=1 (NCHW); under the channels-last
            # layout policy (layout.py) the channel axis is the last one
            from ... import layout as layout_mod

            axis = -1 if layout_mod.is_channel_last() else 1
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True)

    def _shape_hint(self, x, *args):
        if self.gamma.shape and self.gamma.shape[0] == 0:
            channels = x.shape[self._axis]
            for p in (self.gamma, self.beta, self.running_mean,
                      self.running_var):
                p.shape = (channels,)

    def cast(self, dtype):
        if dtype in ("float16", "bfloat16"):
            dtype = "float32"  # stats stay fp32 (parity: basic_layers.py cast)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        out, new_mean, new_var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var, **self._kwargs)
        from ... import autograd

        if autograd.is_training():
            record_aux_update(self.running_mean, new_mean)
            record_aux_update(self.running_var, new_var)
        return out

    def __repr__(self):
        shape = self.gamma.shape
        return "BatchNorm(axis=%s, in_channels=%s)" % (
            self._axis, shape[0] if shape else None)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (parity: contrib SyncBatchNorm,
    ``src/operator/contrib/sync_batch_norm.cc``).

    On TPU, batch stats are reduced with ``jax.lax.pmean`` automatically when
    the forward runs inside a ``shard_map``/pjit data-parallel region — the
    op's mean/var become global means because XLA inserts the collective.
    Single-device semantics are identical to BatchNorm.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", prefix=None,
                 params=None):
        super().__init__(1, momentum, epsilon, center, scale,
                         use_global_stats, beta_initializer,
                         gamma_initializer, running_mean_initializer,
                         running_variance_initializer, in_channels,
                         prefix=prefix, params=params)


class LayerNorm(HybridBlock):
    """Parity: nn.LayerNorm."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _shape_hint(self, x, *args):
        if self.gamma.shape and self.gamma.shape[0] == 0:
            channels = x.shape[self._axis]
            self.gamma.shape = (channels,)
            self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        return "LayerNorm(axis=%s, eps=%s)" % (self._axis, self._epsilon)


class GroupNorm(HybridBlock):
    """Parity: nn.GroupNorm."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _shape_hint(self, x, *args):
        if self.gamma.shape and self.gamma.shape[0] == 0:
            self.gamma.shape = (x.shape[1],)
            self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    """Parity: nn.InstanceNorm."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def _shape_hint(self, x, *args):
        if self.gamma.shape and self.gamma.shape[0] == 0:
            self.gamma.shape = (x.shape[1],)
            self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Parity: nn.Embedding — gathers rows of a (input_dim, output_dim) table."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return "Embedding(%s -> %s)" % (self._input_dim, self._output_dim)


class Flatten(HybridBlock):
    """Parity: nn.Flatten."""

    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class Concurrent(Sequential):
    """Run children on the same input, concat outputs on ``axis``
    (parity: gluon/contrib/nn Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as F
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent (parity: gluon/contrib/nn HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Lambda(Block):
    """Wrap an nd-level function (parity: nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            if not hasattr(nd, function):
                raise MXNetError("function %s not found in mx.nd" % function)
            self._func = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")
        else:
            raise MXNetError("function must be str or callable")

    def forward(self, *args):
        return self._func(*args)

    def __repr__(self):
        return "Lambda(%s)" % self._func_name


class HybridLambda(HybridBlock):
    """Wrap an F-level function (parity: nn.HybridLambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func = None
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "lambda")
        else:
            raise MXNetError("function must be str or callable")

    def hybrid_forward(self, F, x, *args):
        if self._func is None:
            return getattr(F, self._func_name)(x, *args)
        return self._func(F, x, *args)

    def __repr__(self):
        return "HybridLambda(%s)" % self._func_name
